"""Sharded weight-update engine (parallel/grad_sync.py): ZeRO-1 trajectory
parity against the dense oracle, sharded optimizer-state memory, overlap
scheduling inside grad accumulation, partition-aware clipping, comm
telemetry, and dense<->zero1 checkpoint resharding (ISSUE 5)."""

import logging

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu import telemetry as tel
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import ClusterConfig, TrainConfig
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.parallel.grad_sync import (BucketLayout, GradSyncEngine,
                                        STRATEGIES,
                                        opt_state_bytes_per_device)
from dtf_tpu.parallel.mesh import make_mesh
from dtf_tpu.train.trainer import (Trainer, init_state, make_train_step,
                                   put_global_batch)


def mlp_batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.random((n, 784)).astype(np.float32),
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)])


def leaves_close(a, b, **kw):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(jax.device_get(la)),
                                   np.asarray(jax.device_get(lb)), **kw)


def make_engine(strategy, opt, mesh, model=None, **kw):
    model = model or MnistMLP(init_scale="fan_in")
    return GradSyncEngine(strategy, opt, mesh, **kw).prepare(
        jax.eval_shape(model.init, jax.random.key(1)))


class TestBucketLayout:
    def test_roundtrip_uneven_leaves(self):
        """Mixed shapes/dtypes whose sizes don't divide anything cleanly
        must survive flatten -> unflatten bitwise, padding trimmed."""
        tree = {"a": jnp.arange(7, dtype=jnp.float32).reshape(7),
                "b": {"w": jnp.ones((13, 3), jnp.bfloat16) * 2,
                      "s": jnp.array(5.0, jnp.float32)},
                "c": jnp.arange(130, dtype=jnp.float32)}
        layout = BucketLayout.build(tree, n_shards=8, bucket_bytes=64)
        vecs = layout.flatten(tree)
        assert len(vecs) == len(layout.padded) >= 2
        for k, v in vecs.items():
            assert v.shape[0] % 8 == 0          # reduce_scatter divides
            assert v.shape[0] % 128 == 0        # elastic-stable quantum
        back = layout.unflatten(vecs)
        assert jax.tree_util.tree_structure(back) == \
            jax.tree_util.tree_structure(tree)
        for la, lb in zip(jax.tree_util.tree_leaves(tree),
                          jax.tree_util.tree_leaves(back)):
            assert la.dtype == lb.dtype
            np.testing.assert_array_equal(np.asarray(la, np.float32),
                                          np.asarray(lb, np.float32))

    def test_padding_is_axis_size_stable(self):
        """The lcm(N, 128) quantum makes padded (global) bucket shapes
        identical for every power-of-two axis up to 128 — the property
        the elastic 4->2 optimizer-state reshard rests on."""
        tree = {"w": jnp.zeros((777,)), "v": jnp.zeros((513,))}
        shapes = {n: BucketLayout.build(tree, n, 1 << 20).padded
                  for n in (1, 2, 4, 8)}
        assert len(set(shapes.values())) == 1

    def test_unflatten_cast_false_keeps_f32(self):
        tree = {"w": jnp.ones((4,), jnp.bfloat16)}
        layout = BucketLayout.build(tree, 2, 1 << 20)
        back = layout.unflatten(layout.flatten(tree), cast=False)
        assert back["w"].dtype == jnp.float32

    def test_strategy_literals_pinned(self):
        """config.py and telemetry/report.py carry literal mirrors of
        STRATEGIES (they must import without jax); pin them."""
        assert STRATEGIES == ("dense", "zero1", "zero1_overlap")
        import inspect

        from dtf_tpu.telemetry import report
        assert '("dense", "zero1", "zero1_overlap")' in \
            inspect.getsource(report.render)
        with pytest.raises(ValueError, match="grad_sync"):
            TrainConfig(grad_sync="zero3")


class TestZero1MatchesDense:
    @pytest.mark.parametrize("opt_name", ["sgd", "momentum", "adam",
                                          "lamb"])
    def test_multi_step_param_parity(self, mesh8, opt_name):
        """zero1's reduce-scatter + sharded update + all-gather must
        reproduce the dense pmean + replicated update trajectory.  lamb
        rides the same bar: its per-tensor trust ratios are rebuilt from
        shard segment sums + psum, so the sharded update must still
        match dense LAMB within float reduction order."""
        mk = {"sgd": lambda: optim.sgd(0.1),
              "momentum": lambda: optim.momentum(0.05),
              "adam": lambda: optim.adam(1e-3),
              "lamb": lambda: optim.lamb(1e-3)}[opt_name]
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for strat in ("dense", "zero1"):
            opt = mk()
            eng = (make_engine(strat, opt, mesh8, bucket_mb=0.1)
                   if strat != "dense" else None)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_sync=eng)
            b = put_global_batch(mesh8, batch)
            for i in range(3):
                state, m = step(state, b, jax.random.key(i))
            out[strat] = (state["params"], float(m["loss"]))
        assert out["dense"][1] == pytest.approx(out["zero1"][1], rel=2e-5)
        leaves_close(out["dense"][0], out["zero1"][0], rtol=2e-5, atol=1e-6)

    def test_overlap_inside_grad_accum_matches(self, mesh8):
        """zero1_overlap reduce-scatters per MICROBATCH inside the
        accumulation scan; sum-of-means == mean-of-sums, so the params
        must match the dense accumulated step."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for strat in ("dense", "zero1_overlap"):
            opt = optim.adam(1e-3)
            eng = (make_engine(strat, opt, mesh8, bucket_mb=0.1)
                   if strat != "dense" else None)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_sync=eng,
                                   grad_accum=4)
            state, m = step(state, put_global_batch(mesh8, batch),
                            jax.random.key(0))
            out[strat] = state["params"]
        leaves_close(out["dense"], out["zero1_overlap"],
                     rtol=2e-5, atol=1e-6)

    def test_lamb_zero1_composes_with_clip_and_overlap(self, mesh8):
        """clip(lamb) under zero1_overlap + grad accumulation: the global
        clip norm AND the per-tensor trust norms are both psum'd from
        shard contributions; the trajectory must match the dense clipped
        LAMB step."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for strat in ("dense", "zero1_overlap"):
            opt = optim.clip_by_global_norm(optim.lamb(1e-3), 0.5)
            eng = (make_engine(strat, opt, mesh8, bucket_mb=0.1)
                   if strat != "dense" else None)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_sync=eng,
                                   grad_accum=4)
            b = put_global_batch(mesh8, batch)
            for i in range(2):
                state, m = step(state, b, jax.random.key(i))
            out[strat] = state["params"]
        leaves_close(out["dense"], out["zero1_overlap"],
                     rtol=5e-5, atol=1e-6)

    def test_lamb_sharded_state_born_sharded(self, mesh8):
        """LAMB's inner-adam moments under zero1 keep the ordinary
        sharded bucket shapes (1/N per device) — the dense<->zero1
        checkpoint reshard path depends on that layout."""
        model = MnistMLP(init_scale="fan_in")
        opt = optim.lamb(1e-3)
        eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1)
        sharded = init_state(model, opt, seed=1, mesh=mesh8,
                             grad_sync=eng)["opt_state"]
        dense = init_state(model, opt, seed=1, mesh=mesh8)["opt_state"]
        assert opt_state_bytes_per_device(sharded) \
            < 0.25 * opt_state_bytes_per_device(dense)

    def test_lm_workload_parity(self, mesh8):
        """The acceptance's second workload: a tiny GPT causal-LM step,
        dense vs zero1."""
        from dtf_tpu.models.gpt import GPT, GPTConfig

        model = GPT(GPTConfig.tiny())
        toks = np.asarray(
            np.random.default_rng(0).integers(0, 128, (16, 64)), np.int32)
        out = {}
        for strat in ("dense", "zero1"):
            opt = optim.adam(1e-3)
            eng = None
            if strat != "dense":
                eng = GradSyncEngine(strat, opt, mesh8,
                                     bucket_mb=0.25).prepare(
                    jax.eval_shape(model.init, jax.random.key(1)))
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_sync=eng)
            b = put_global_batch(mesh8, toks)
            for i in range(2):
                state, m = step(state, b, jax.random.key(i))
            out[strat] = (state["params"], float(m["loss"]))
        assert out["dense"][1] == pytest.approx(out["zero1"][1], rel=1e-4)
        leaves_close(out["dense"][0], out["zero1"][0], rtol=1e-4, atol=1e-5)

    def test_bf16_comm_dtype_close_not_exact(self, mesh8):
        """--grad_comm_dtype bf16: mean-preserving reduced-precision wire
        stays within bf16 tolerance of the exact path (and composes with
        both strategies)."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for cd in (None, "bf16"):
            opt = optim.adam(1e-3)
            eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1,
                              comm_dtype=cd)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_sync=eng)
            state, _ = step(state, put_global_batch(mesh8, batch),
                            jax.random.key(0))
            out[cd] = state["params"]
        leaves_close(out[None], out["bf16"], rtol=2e-2, atol=2e-3)

    def test_guard_skips_poisoned_step_and_keeps_state(self, mesh8):
        """A NaN batch under zero1: the where-selected skip leaves params
        AND the sharded optimizer state untouched, counters bump — same
        contract as the dense lax.cond skip."""
        opt = optim.adam(1e-3)
        model = MnistMLP(init_scale="fan_in")
        eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1)
        state = init_state(model, opt, seed=1, mesh=mesh8, guard=True,
                           grad_sync=eng)
        step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                               donate=False, guard=True, grad_sync=eng)
        x, y = mlp_batch()
        x[3, 5] = np.nan
        new, m = step(state, put_global_batch(mesh8, (x, y)),
                      jax.random.key(0))
        assert int(m["nonfinite"]) == 1
        assert int(new["skipped"]) == 1 and int(new["bad_streak"]) == 1
        leaves_close(state["params"], new["params"])
        leaves_close(state["opt_state"], new["opt_state"])


class TestPartitionAwareClip:
    def test_clip_psums_to_global_norm(self, mesh8):
        """The axis-aware clip's norm over disjoint shards equals the
        local norm over the full vector (satellite: zero1 clipping must
        apply the same scale as dense)."""
        from jax.sharding import PartitionSpec as P

        from dtf_tpu.parallel.collectives import shard_map_fn

        opt = optim.clip_by_global_norm(optim.sgd(1.0), 1.0, axis="data")
        v = np.linspace(-2, 3, 128).astype(np.float32)

        def f(shard):
            upd, _ = opt.update({"g": shard}, (), None)
            return upd["g"]

        g = shard_map_fn(f, mesh=mesh8, in_specs=P("data"),
                         out_specs=P("data"))
        sharded = np.asarray(g(v))
        ref_opt = optim.clip_by_global_norm(optim.sgd(1.0), 1.0)
        ref, _ = ref_opt.update({"g": jnp.asarray(v)}, (), None)
        np.testing.assert_allclose(sharded, np.asarray(ref["g"]),
                                   rtol=1e-6, atol=1e-7)

    @pytest.mark.parametrize("max_norm", [0.05, 10.0])
    def test_zero1_clip_trajectory_matches_dense(self, mesh8, max_norm):
        """Active (tiny max_norm) and inactive clipping: the engine
        re-derives the wrapper with the data axis, so zero1 == dense."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for strat in ("dense", "zero1"):
            opt = optim.clip_by_global_norm(optim.sgd(0.5), max_norm)
            eng = (make_engine(strat, opt, mesh8, bucket_mb=0.1)
                   if strat != "dense" else None)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_sync=eng)
            state, _ = step(state, put_global_batch(mesh8, batch),
                            jax.random.key(0))
            out[strat] = state["params"]
        leaves_close(out["dense"], out["zero1"], rtol=1e-6, atol=1e-7)


class TestShardedOptimizerState:
    def test_state_born_sharded_and_bytes_drop(self, mesh8):
        """Adam moments under zero1: bucket vectors sharded P('data'),
        measured per-device bytes ~(N-1)/N below dense (the ISSUE
        acceptance's memory claim)."""
        opt = optim.adam(1e-3)
        model = MnistMLP(init_scale="fan_in")
        dense = init_state(model, opt, seed=1, mesh=mesh8)
        eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1)
        sharded = init_state(model, opt, seed=1, mesh=mesh8, grad_sync=eng)
        m0 = sharded["opt_state"]["m"]
        for k, v in m0.items():
            assert v.ndim == 1
            assert tuple(v.sharding.spec) == ("data",)
            assert v.addressable_shards[0].data.shape[0] == v.shape[0] // 8
        d = opt_state_bytes_per_device(dense["opt_state"])
        z = opt_state_bytes_per_device(sharded["opt_state"])
        assert z < d * 0.25       # 1/8 for moments + padding + scalars
        assert z > 0

    def test_comm_stats_scale_with_overlap_microbatches(self, mesh8):
        """zero1_overlap reduce-scatters once per MICROBATCH: the wire-
        bytes gauge must scale its RS term by grad_accum (zero1 doesn't —
        its single scatter runs on the accumulated gradients)."""
        opt = optim.adam(1e-3)
        z1 = make_engine("zero1", opt, mesh8, bucket_mb=0.1)
        zo = make_engine("zero1_overlap", opt, mesh8, bucket_mb=0.1)
        total = sum(z1.layout.padded)
        assert z1.comm_stats(4)["grad_sync_bytes"] == total * 8   # 4+4
        assert zo.comm_stats(1)["grad_sync_bytes"] == total * 8
        assert zo.comm_stats(4)["grad_sync_bytes"] == total * (4 * 4 + 4)

    def test_rejects_adafactor_but_accepts_lamb(self, mesh8):
        """adafactor's factored moments genuinely don't shard over the
        flat bucket layout — loud rejection naming the dense fallback
        cost.  LAMB no longer rejects: its trust-ratio norms are psum'd
        shard-aware (the large-batch scenario-cell unlock)."""
        with pytest.raises(ValueError, match="adafactor"):
            make_engine("zero1", optim.adafactor(1e-2), mesh8)
        with pytest.raises(ValueError, match="dense"):
            make_engine("zero1", optim.adafactor(1e-2), mesh8)
        eng = make_engine("zero1", optim.lamb(1e-3), mesh8, bucket_mb=0.1)
        assert eng.layout is not None

    def test_rejects_model_axes_mesh(self, mesh_2d):
        opt = optim.adam(1e-3)
        eng = GradSyncEngine("zero1", opt, mesh_2d, bucket_mb=0.1)
        with pytest.raises(ValueError, match="data-parallel only"):
            make_train_step(MnistMLP().loss, opt, mesh_2d, mode="explicit",
                            grad_sync=eng.prepare(
                                jax.eval_shape(MnistMLP().init,
                                               jax.random.key(1))))

    def test_engine_requires_explicit_mode(self, mesh8):
        opt = optim.adam(1e-3)
        eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1)
        with pytest.raises(ValueError, match="explicit"):
            make_train_step(MnistMLP().loss, opt, mesh8, mode="implicit",
                            grad_sync=eng)


class TestXlaOverlapPreset:
    def test_preset_appends_libtpu_args_idempotently(self, monkeypatch):
        """--xla_overlap rides LIBTPU_INIT_ARGS (inert off-TPU, read at
        libtpu load): applied once, appended to an operator's own args,
        and a second call adds nothing."""
        import os

        from dtf_tpu.cluster import apply_xla_overlap_preset

        monkeypatch.setenv("LIBTPU_INIT_ARGS", "--xla_custom_flag=1")
        first = apply_xla_overlap_preset()
        assert "--xla_custom_flag=1" in first
        assert "--xla_tpu_enable_latency_hiding_scheduler=true" in first
        assert apply_xla_overlap_preset() == first     # idempotent
        assert os.environ["LIBTPU_INIT_ARGS"] == first
        # precedence: the preset is PREPENDED — libtpu takes the LAST
        # value, so an operator's explicit =false must survive the preset
        monkeypatch.setenv(
            "LIBTPU_INIT_ARGS",
            "--xla_tpu_enable_latency_hiding_scheduler=false")
        merged = apply_xla_overlap_preset()
        assert merged.rindex("scheduler=false") > \
            merged.rindex("scheduler=true")

    def test_cluster_config_flag_parses(self):
        from dtf_tpu.config import parse_args

        cluster_cfg, _ = parse_args(["--xla_overlap"])
        assert cluster_cfg.xla_overlap is True


def make_trainer(mesh, logdir, strategy, resume=False, seed=1,
                 bucket_mb=0.1):
    tel.reset()
    cfg = TrainConfig(batch_size=64, learning_rate=1e-3, epochs=1,
                      log_frequency=20, seed=seed, logdir=str(logdir),
                      checkpoint_every=2, resume=resume,
                      grad_sync=strategy, grad_bucket_mb=bucket_mb,
                      optimizer="adam")
    cluster = Cluster(config=ClusterConfig(), mesh=mesh)
    return Trainer(cluster, MnistMLP(init_scale="fan_in"),
                   optim.adam(1e-3), cfg)


class TestTrainerIntegration:
    def test_auto_switch_to_explicit_and_gauges(self, mesh8, tmp_path):
        t = make_trainer(mesh8, tmp_path, "zero1")
        assert t.mode == "explicit"
        snap = tel.get_registry().snapshot()
        assert snap["comm/strategy_idx"]["value"] == STRATEGIES.index("zero1")
        assert snap["comm/data_axis_size"]["value"] == 8
        assert snap["comm/bucket_count"]["value"] >= 1
        assert snap["comm/grad_sync_bytes"]["value"] > 0
        assert snap["comm/optimizer_state_bytes"]["value"] > 0

    def test_fit_trajectory_matches_dense(self, mesh8, tmp_path):
        """Trainer-level MNIST A/B (the full-suite lane's fast twin):
        same seed, same batches — zero1 cost within float tolerance of
        dense, measured optimizer bytes ~1/8."""
        from dtf_tpu.data import load_mnist

        costs, bytes_ = {}, {}
        for strat in ("dense", "zero1"):
            t = make_trainer(mesh8, tmp_path / strat, strat)
            t.fit(load_mnist(seed=1), epochs=1, max_steps=6)
            costs[strat] = float(t.last_metrics["loss"])
            bytes_[strat] = tel.get_registry().snapshot()[
                "comm/optimizer_state_bytes"]["value"]
            t.ckpt.close()
        assert costs["zero1"] == pytest.approx(costs["dense"], rel=1e-4)
        assert bytes_["zero1"] < bytes_["dense"] * 0.25

    def test_manifest_records_strategy(self, mesh8, tmp_path):
        t = make_trainer(mesh8, tmp_path, "zero1")
        from dtf_tpu.data import load_mnist
        t.fit(load_mnist(seed=1), epochs=1, max_steps=2)
        t.ckpt.close()
        meta = t.ckpt.manifest_meta(t.ckpt.latest_step())
        assert meta["run"] == {"grad_sync": "zero1", "data_axis": 8,
                               "grad_bucket_mb": 0.1,
                               "grad_comm_dtype": "f32"}

    def test_wire_dtype_change_logged_on_restore(self, mesh8, tmp_path,
                                                 caplog):
        """ISSUE 6 satellite: the manifest records grad_comm_dtype and a
        resume under a DIFFERENT wire format logs the attribution line
        (post-mortems need to tell wire noise from regressions)."""
        from dtf_tpu.data import load_mnist

        t = make_trainer(mesh8, tmp_path / "run", "zero1")
        t.fit(load_mnist(seed=1), epochs=1, max_steps=2)
        t.ckpt.close()
        meta = t.ckpt.manifest_meta(t.ckpt.latest_step())
        assert meta["run"]["grad_comm_dtype"] == "f32"

        tel.reset()
        cfg = TrainConfig(batch_size=64, learning_rate=1e-3, epochs=1,
                          log_frequency=20, seed=1,
                          logdir=str(tmp_path / "run"),
                          checkpoint_every=2, resume=True,
                          grad_sync="zero1", grad_bucket_mb=0.1,
                          grad_comm_dtype="int8", optimizer="adam")
        cluster = Cluster(config=ClusterConfig(), mesh=mesh8)
        with caplog.at_level(logging.WARNING, logger="dtf_tpu"):
            t2 = Trainer(cluster, MnistMLP(init_scale="fan_in"),
                         optim.adam(1e-3), cfg)
        assert t2._host_step == 2      # same layout: ordinary restore
        assert any("grad_comm_dtype" in r.message and "f32" in r.message
                   for r in caplog.records)
        t2.ckpt.close()


class TestCrossStrategyRestore:
    def test_dense_to_zero1_and_back(self, mesh8, tmp_path, caplog):
        """dense -> zero1 -> dense restore chain: each hop converts the
        optimizer-state layout, logs the reshard, and the final trajectory
        equals an uninterrupted dense run."""
        from dtf_tpu.data import load_mnist

        t = make_trainer(mesh8, tmp_path / "run", "dense")
        t.fit(load_mnist(seed=1), epochs=1, max_steps=4)
        t.ckpt.close()

        with caplog.at_level(logging.WARNING, logger="dtf_tpu"):
            t2 = make_trainer(mesh8, tmp_path / "run", "zero1", resume=True)
        assert t2._host_step == 4
        assert any("saved under --grad_sync dense" in r.message
                   for r in caplog.records)
        m_leaf = jax.tree_util.tree_leaves(t2.state["opt_state"]["m"])[0]
        assert tuple(m_leaf.sharding.spec) == ("data",)
        t2.fit(load_mnist(seed=1), epochs=1, max_steps=8)
        mixed = float(t2.last_metrics["loss"])
        t2.ckpt.close()

        # The dense resume deliberately uses a DIFFERENT --grad_bucket_mb
        # than the zero1 writer: the reshard must rebuild the WRITER's
        # bucket layout from the manifest, not assume this run's.
        t3 = make_trainer(mesh8, tmp_path / "run", "dense", resume=True,
                          bucket_mb=4.0)
        assert t3._host_step == 8
        # moments are back to param-shaped replicated leaves
        assert t3.state["opt_state"]["m"]["l1"]["w"].shape == (784, 100)

        ref = make_trainer(mesh8, tmp_path / "ref", "dense")
        ref.fit(load_mnist(seed=1), epochs=1, max_steps=8)
        assert mixed == pytest.approx(float(ref.last_metrics["loss"]),
                                      rel=1e-4)
        ref.ckpt.close()
        t3.ckpt.close()

    def test_zero1_bucket_resize_reshards(self, mesh8, tmp_path):
        """zero1 -> zero1 with a changed --grad_bucket_mb is also a layout
        change: the restore goes writer-layout -> dense -> current-layout
        and the trajectory survives byte-for-byte in value terms."""
        from dtf_tpu.data import load_mnist

        t = make_trainer(mesh8, tmp_path / "run", "zero1", bucket_mb=0.1)
        t.fit(load_mnist(seed=1), epochs=1, max_steps=4)
        m_before = jax.device_get(t.state["opt_state"]["m"])
        t.ckpt.close()
        t2 = make_trainer(mesh8, tmp_path / "run", "zero1", resume=True,
                          bucket_mb=0.5)
        assert t2._host_step == 4
        eng = t2._grad_sync_engine
        # values round-trip through the writer layout: compare densified
        dense_after = eng.unshard_opt_state(t2.state["opt_state"])["m"]
        eng_writer = make_engine("zero1", optim.adam(1e-3), mesh8,
                                 bucket_mb=0.1)
        dense_before = eng_writer.unshard_opt_state(
            {"m": m_before})["m"]
        leaves_close(dense_before, dense_after)
        t2.ckpt.close()

    def test_elastic_shrink_4_to_2_reshards_opt_state(self, tmp_path,
                                                      caplog):
        """Elastic 4->2: zero1 optimizer state saved on a 4-way data axis
        restores onto a 2-way mesh — same global array shapes (the
        lcm(N,128) padding quantum), only the sharding changes — and the
        reshard is logged."""
        from dtf_tpu.train.checkpoint import CheckpointManager

        model = MnistMLP(init_scale="fan_in")
        opt = optim.adam(1e-3)
        devs = jax.devices()
        mesh4 = make_mesh("data=4", devs[:4])
        mesh2 = make_mesh("data=2", devs[:2])

        eng4 = GradSyncEngine("zero1", opt, mesh4, bucket_mb=0.1).prepare(
            jax.eval_shape(model.init, jax.random.key(1)))
        state4 = init_state(model, opt, seed=1, mesh=mesh4, grad_sync=eng4)
        # make the moments non-trivial so the value comparison means something
        step4 = make_train_step(model.loss, opt, mesh4, mode="explicit",
                                donate=False, grad_sync=eng4)
        state4, _ = step4(state4, put_global_batch(mesh4, mlp_batch()),
                          jax.random.key(0))
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False,
                                run_meta={"grad_sync": "zero1",
                                          "data_axis": 4})
        mgr.save(1, state4, force=True)
        mgr.wait()

        eng2 = GradSyncEngine("zero1", opt, mesh2, bucket_mb=0.1).prepare(
            jax.eval_shape(model.init, jax.random.key(1)))
        template = init_state(model, opt, seed=2, mesh=mesh2,
                              grad_sync=eng2)
        mgr2 = CheckpointManager(str(tmp_path / "ckpt"), async_save=False,
                                 run_meta={"grad_sync": "zero1",
                                           "data_axis": 2})
        with caplog.at_level(logging.WARNING, logger="dtf_tpu"):
            restored, step = mgr2.restore_robust(template)
        assert step == 1
        assert any("2-way" in r.message and "4-way" not in ""  # noqa: SIM300
                   or "data axis" in r.message for r in caplog.records)
        for k, v in restored["opt_state"]["m"].items():
            assert v.shape == state4["opt_state"]["m"][k].shape
            assert v.addressable_shards[0].data.shape[0] == v.shape[0] // 2
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(v)),
                np.asarray(jax.device_get(state4["opt_state"]["m"][k])))
        mgr.close()
        mgr2.close()
