"""Ring attention (sequence parallelism) vs full attention on the 8-device
CPU mesh: exactness, causal masking across chunk boundaries, gradients
through the ppermute ring, composition with data parallelism and BERT."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.nn.attention import MultiHeadAttention, dot_product_attention
from dtf_tpu.ops.ring_attention import ring_attention, ring_attention_impl
from dtf_tpu.parallel.mesh import make_mesh


@pytest.fixture()
def seq_mesh():
    return make_mesh("seq=8")


@pytest.fixture()
def data_seq_mesh():
    return make_mesh("data=2,seq=4")


def rand_qkv(key, shape, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in (kq, kk, kv))


def naive_causal(q, k, v):
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
    return dot_product_attention(q, k, v, mask=mask)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, seq_mesh, causal):
        q, k, v = rand_qkv(jax.random.key(0), (2, 64, 4, 16))
        out = ring_attention(q, k, v, seq_mesh, causal=causal)
        ref = naive_causal(q, k, v) if causal else dot_product_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_kv_mask_matches_full_attention(self, seq_mesh, causal):
        """Key-padding masks: the validity chunks rotate with their K/V
        chunks, so padded keys stay masked on every device."""
        q, k, v = rand_qkv(jax.random.key(7), (3, 64, 4, 16))
        valid = jnp.stack([jnp.arange(64) < 40,     # padded tail
                           jnp.arange(64) >= 16,    # whole first chunk pad
                           jnp.ones(64, bool)])
        out = ring_attention(q, k, v, seq_mesh, causal=causal,
                             kv_mask=valid)
        mask = valid[:, None, None, :]
        if causal:
            mask = mask & jnp.tril(jnp.ones((64, 64), bool))[None, None]
        ref = dot_product_attention(q, k, v, mask=mask)
        if causal:
            # rows 0..15 of batch 1 see no keys under causal+mask:
            # undefined by contract — compare the rest
            out, ref = out[:, 16:], ref[:, 16:]
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_impl_accepts_key_padding_mask(self, seq_mesh):
        q, k, v = rand_qkv(jax.random.key(8), (2, 32, 4, 8))
        valid = jnp.stack([jnp.ones(32, bool), jnp.arange(32) < 24])
        impl = ring_attention_impl(seq_mesh)
        out = impl(q, k, v, valid[:, None, None, :])
        ref = dot_product_attention(q, k, v, valid[:, None, None, :])
        np.testing.assert_allclose(out, ref, atol=2e-5)
        with pytest.raises(ValueError, match="per-query"):
            impl(q, k, v, jnp.ones((2, 1, 32, 32), bool))

    def test_composes_with_data_axis(self, data_seq_mesh):
        q, k, v = rand_qkv(jax.random.key(1), (4, 32, 2, 8))
        out = ring_attention(q, k, v, data_seq_mesh)
        np.testing.assert_allclose(out, dot_product_attention(q, k, v),
                                   atol=2e-5)

    def test_under_jit_with_sharded_inputs(self, seq_mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        q, k, v = rand_qkv(jax.random.key(2), (1, 64, 2, 8))
        s = NamedSharding(seq_mesh, P(None, "seq", None, None))
        qs, ks, vs = (jax.device_put(x, s) for x in (q, k, v))

        @jax.jit
        def f(q, k, v):
            return ring_attention(q, k, v, seq_mesh, causal=True)

        out = f(qs, ks, vs)
        assert out.sharding.spec == s.spec       # stays seq-sharded
        np.testing.assert_allclose(out, naive_causal(q, k, v), atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_flow_through_ring(self, seq_mesh, causal):
        q, k, v = rand_qkv(jax.random.key(3), (1, 32, 2, 8))

        def f_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, seq_mesh,
                                          causal=causal) ** 2)

        def f_ref(q, k, v):
            ref = naive_causal(q, k, v) if causal else \
                dot_product_attention(q, k, v)
            return jnp.sum(ref ** 2)

        gr = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gr, gn, "qkv"):
            np.testing.assert_allclose(a, b, atol=5e-5,
                                       err_msg=f"d{name}")

    def test_bf16(self, seq_mesh):
        q, k, v = rand_qkv(jax.random.key(4), (1, 32, 2, 8), jnp.bfloat16)
        out = ring_attention(q, k, v, seq_mesh)
        ref = dot_product_attention(q.astype(jnp.float32),
                                    k.astype(jnp.float32),
                                    v.astype(jnp.float32))
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=2e-2)

    def test_indivisible_seq_raises(self, seq_mesh):
        q, k, v = rand_qkv(jax.random.key(5), (1, 30, 2, 8))
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(q, k, v, seq_mesh)

    def test_missing_axis_raises(self):
        mesh = make_mesh("data=8")
        q, k, v = rand_qkv(jax.random.key(6), (1, 32, 2, 8))
        with pytest.raises(ValueError, match="no 'seq' axis"):
            ring_attention(q, k, v, mesh)


class TestRingInMHA:
    def test_attn_impl_matches_plain_mha(self, seq_mesh):
        impl = ring_attention_impl(seq_mesh)
        mha_ring = MultiHeadAttention(dim=32, num_heads=4, attn_impl=impl)
        mha_ref = MultiHeadAttention(dim=32, num_heads=4)
        params = mha_ref.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2, 64, 32))
        np.testing.assert_allclose(mha_ring.apply(params, x),
                                   mha_ref.apply(params, x), atol=2e-5)

    def test_bert_with_ring_attention_trains(self, data_seq_mesh):
        """BERT with ring attention: one DP+SP train step end to end."""
        from dtf_tpu import optim
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        cfg = BertConfig.tiny(attn_impl=ring_attention_impl(data_seq_mesh))
        model = BertMLM(cfg)
        opt = optim.adam(1e-3)
        state = init_state(model, opt, seed=0, mesh=data_seq_mesh)
        step = make_train_step(model.loss, opt, data_seq_mesh, donate=False)
        toks = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, cfg.max_len)).astype(np.int32)
        batch = put_global_batch(data_seq_mesh, toks)
        state, metrics = step(state, batch, jax.random.key(0))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state["step"]) == 1
