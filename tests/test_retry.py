"""utils/retry.py: bounded retry + exponential backoff with seeded jitter
(the shared policy behind cluster bootstrap, the data path and the restart
supervisor)."""

import pytest

from dtf_tpu.utils.retry import Backoff, RetryExhausted, retry_call

pytestmark = pytest.mark.chaos


class FlakyThenOk:
    """Raises ``exc`` for the first ``failures`` calls, then returns 42."""

    def __init__(self, failures, exc=OSError("transient")):
        self.failures = failures
        self.exc = exc
        self.calls = 0

    def __call__(self):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc
        return 42


class TestBackoff:
    def test_exponential_capped(self):
        b = Backoff(base_s=1.0, max_s=4.0, factor=2.0, jitter=0.0)
        assert [b.delay_s(k) for k in range(4)] == [1.0, 2.0, 4.0, 4.0]

    def test_jitter_bounded_and_seeded(self):
        a = Backoff(base_s=1.0, max_s=64.0, jitter=0.25, seed=7)
        b = Backoff(base_s=1.0, max_s=64.0, jitter=0.25, seed=7)
        da = [a.delay_s(k) for k in range(6)]
        db = [b.delay_s(k) for k in range(6)]
        assert da == db                     # same seed -> same delays
        for k, d in enumerate(da):
            nominal = min(2.0 ** k, 64.0)
            assert 0.75 * nominal <= d <= 1.25 * nominal

    def test_rejects_bad_params(self):
        with pytest.raises(ValueError, match="jitter"):
            Backoff(jitter=1.5)
        with pytest.raises(ValueError, match=">= 0"):
            Backoff(base_s=-1.0)


class TestRetryCall:
    def test_succeeds_after_transients(self):
        sleeps = []
        fn = FlakyThenOk(2)
        out = retry_call(fn, attempts=5,
                         backoff=Backoff(base_s=0.1, max_s=1.0, jitter=0.0),
                         sleep=sleeps.append)
        assert out == 42 and fn.calls == 3
        assert sleeps == [0.1, 0.2]        # exact schedule, no real sleeping

    def test_exhaustion_is_loud_and_terminal(self):
        fn = FlakyThenOk(99)
        with pytest.raises(RetryExhausted, match="data fetch.*3 attempt"):
            retry_call(fn, attempts=3, what="data fetch",
                       backoff=Backoff(base_s=0, jitter=0.0),
                       sleep=lambda s: None)
        assert fn.calls == 3               # bounded: no silent infinite loop
        try:
            retry_call(FlakyThenOk(99), attempts=2,
                       backoff=Backoff(base_s=0, jitter=0.0),
                       sleep=lambda s: None)
        except RetryExhausted as e:
            assert isinstance(e.__cause__, OSError)   # root cause chained
            assert e.attempts == 2

    def test_non_matching_exception_is_terminal(self):
        """Config errors must not burn the retry budget."""
        fn = FlakyThenOk(1, exc=ValueError("bad config"))
        with pytest.raises(ValueError, match="bad config"):
            retry_call(fn, attempts=5, retry_on=(OSError,),
                       sleep=lambda s: None)
        assert fn.calls == 1

    def test_on_retry_observes_each_failure(self):
        seen = []
        retry_call(FlakyThenOk(2), attempts=3,
                   backoff=Backoff(base_s=0, jitter=0.0),
                   on_retry=lambda k, e: seen.append((k, type(e).__name__)),
                   sleep=lambda s: None)
        assert seen == [(0, "OSError"), (1, "OSError")]

    def test_rejects_zero_attempts(self):
        with pytest.raises(ValueError, match="attempts"):
            retry_call(lambda: 1, attempts=0)
