"""Block-scaled int8 wire format (parallel/quantize.py) + low-precision
compute paths (nn/lowp.py): encode/decode round-trip bounds, mean
preservation under reduce, stochastic-rounding unbiasedness, non-finite
edge handling feeding the guard, wire-byte accounting, and the
straight-through matmul paths (ISSUE 6)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dtf_tpu import optim
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.parallel import quantize as qz
from dtf_tpu.parallel.collectives import shard_map_fn
from dtf_tpu.parallel.grad_sync import (GradSyncEngine, WIRE_DTYPES,
                                        comm_dtype_of, wire_bytes_per_elem,
                                        wire_dtype_name)
from dtf_tpu.train.trainer import (init_state, make_train_step,
                                   put_global_batch)


def mlp_batch(n=64, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.random((n, 784)).astype(np.float32),
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)])


def make_engine(strategy, opt, mesh, **kw):
    model = MnistMLP(init_scale="fan_in")
    return GradSyncEngine(strategy, opt, mesh, **kw).prepare(
        jax.eval_shape(model.init, jax.random.key(1)))


class TestEncodeDecode:
    def test_roundtrip_error_bounded_by_block_scale(self):
        """Nearest rounding: |decode - v| <= scale/2 per element, where
        scale is the element's OWN block's max/127 — the per-block
        granularity claim (a big block elsewhere must not hurt)."""
        rng = np.random.default_rng(0)
        v = rng.normal(size=(4 * qz.QBLOCK,)).astype(np.float32)
        v[qz.QBLOCK:2 * qz.QBLOCK] *= 1000.0     # one heavy block
        q, s = qz.encode(jnp.asarray(v))
        back = np.asarray(qz.decode(q, s))
        scales = np.repeat(np.asarray(s).reshape(-1), qz.QBLOCK)
        assert np.all(np.abs(back - v) <= scales / 2 + 1e-12)
        # the heavy block must NOT inflate its neighbors' error
        light = slice(0, qz.QBLOCK)
        assert np.abs(back[light] - v[light]).max() < np.abs(v[light]).max() / 200

    def test_relative_rms_error_small_on_gaussian(self):
        rng = np.random.default_rng(1)
        v = jnp.asarray(rng.normal(size=(16 * qz.QBLOCK,)), jnp.float32)
        err = float(qz.error_ratio(qz.encode_error(v)))
        assert 1e-4 < err < 0.02      # ~1e-2 for N(0,1) at 8 bits/block

    def test_zero_block_exact(self):
        v = jnp.zeros((qz.QBLOCK,), jnp.float32)
        back = qz.decode(*qz.encode(v))
        np.testing.assert_array_equal(np.asarray(back), np.zeros(qz.QBLOCK))

    @pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
    def test_nonfinite_never_becomes_finite_garbage(self, bad):
        """A NaN/inf in a block must decode to non-finite values — the
        wire can never launder a poisoned gradient into numbers the
        guard would wave through."""
        v = np.ones((qz.QBLOCK,), np.float32)
        v[7] = bad
        back = np.asarray(qz.decode(*qz.encode(jnp.asarray(v))))
        assert not np.isfinite(back).all()

    def test_misaligned_length_rejected_and_pad_helper(self):
        with pytest.raises(ValueError, match="QBLOCK"):
            qz.encode(jnp.ones((qz.QBLOCK + 1,)))
        padded = qz.pad_to_blocks(jnp.ones((qz.QBLOCK + 1,)))
        assert padded.shape[0] == 2 * qz.QBLOCK
        assert float(padded[qz.QBLOCK + 1:].sum()) == 0.0


class TestStochasticRounding:
    def test_unbiased_over_repeated_draws(self):
        """E[decode(encode(v, stochastic))] -> v: the mean over many
        seeds converges to the input (the property that lets quantized
        gradient noise average out across steps)."""
        rng = np.random.default_rng(2)
        v = jnp.asarray(rng.normal(size=(qz.QBLOCK,)), jnp.float32)
        draws = 400

        @jax.jit
        def one(key):
            return qz.decode(*qz.encode(v, "stochastic", key))

        total = np.zeros(qz.QBLOCK, np.float64)
        for i in range(draws):
            total += np.asarray(one(jax.random.key(i)), np.float64)
        mean = total / draws
        scale = float(jnp.max(jnp.abs(v))) / 127.0
        # std of one draw <= scale; mean of 400 draws ~ scale/20
        assert np.abs(mean - np.asarray(v, np.float64)).max() < scale / 4

    def test_nearest_is_deterministic_stochastic_keyed(self):
        v = jnp.asarray(np.random.default_rng(3).normal(size=(qz.QBLOCK,)),
                        jnp.float32)
        a = qz.decode(*qz.encode(v))
        b = qz.decode(*qz.encode(v))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        s1 = qz.decode(*qz.encode(v, "stochastic", jax.random.key(0)))
        s2 = qz.decode(*qz.encode(v, "stochastic", jax.random.key(0)))
        s3 = qz.decode(*qz.encode(v, "stochastic", jax.random.key(1)))
        np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
        assert np.abs(np.asarray(s1) - np.asarray(s3)).max() > 0

    def test_stochastic_requires_rng(self):
        with pytest.raises(ValueError, match="rng"):
            qz.encode(jnp.ones((qz.QBLOCK,)), "stochastic")

    def test_bad_rounding_rejected(self):
        with pytest.raises(ValueError, match="quant_rounding"):
            qz.check_rounding("banker")


class TestQuantizedCollectives:
    def test_reduce_scatter_sum_matches_dense_mean(self, mesh8):
        """The mean-preservation property: each device ships its (g_i/N)
        quantized; the summed shards must reassemble to the dense mean
        within the per-block quantization bound."""
        n = 8
        length = n * 1000              # NOT a QBLOCK multiple: chunk pad
        rng = np.random.default_rng(4)
        locals_ = rng.normal(size=(n, length)).astype(np.float32)
        dense_mean = locals_.mean(axis=0)

        def f(vs):
            shard = qz.reduce_scatter_quantized(vs[0] * (1.0 / n), "data")
            return qz.all_gather_quantized(shard, "data")[None]

        out = np.asarray(shard_map_fn(
            f, mesh=mesh8, in_specs=P("data"),
            out_specs=P("data"))(locals_))
        for row in out:                # replica-identical by construction
            np.testing.assert_array_equal(row, out[0])
        tol = np.abs(locals_).max() / 127.0 * 2   # one rounding per leg
        np.testing.assert_allclose(out[0], dense_mean, atol=tol)

    def test_indivisible_length_rejected(self, mesh8):
        def f(v):
            return qz.reduce_scatter_quantized(v, "data")[None]
        with pytest.raises(ValueError, match="divisible"):
            shard_map_fn(f, mesh=mesh8, in_specs=P("data"),
                         out_specs=P("data"))(np.ones((8, 12), np.float32))

    def test_all_reduce_mean_quantized_tree(self, mesh8):
        """The dense-path helper: pytree in, replica-identical mean tree
        out, error pair populated."""
        rng = np.random.default_rng(5)
        tree = {"w": rng.normal(size=(8, 37, 5)).astype(np.float32),
                "b": rng.normal(size=(8, 11)).astype(np.float32)}

        def f(t):
            out, err = qz.all_reduce_mean_quantized(
                {"w": t["w"][0], "b": t["b"][0]}, "data")
            return {"w": out["w"][None], "b": out["b"][None]}, err[None]

        got, err = shard_map_fn(
            f, mesh=mesh8, in_specs=({"w": P("data"), "b": P("data")},),
            out_specs=({"w": P("data"), "b": P("data")}, P("data")))(tree)
        for k in ("w", "b"):
            ref = tree[k].mean(axis=0)
            tol = np.abs(tree[k]).max() / 127.0 * 2
            for row in np.asarray(got[k]):
                np.testing.assert_allclose(row, ref, atol=tol)
        assert np.asarray(err).sum() > 0

    def test_wire_elems_accounting(self):
        # 8 chunks of 1000 -> each pads to 4*QBLOCK=1024
        assert qz.wire_elems(8000, 8) == 8 * 1024
        # exact multiples pay zero padding
        assert qz.wire_elems(8 * qz.QBLOCK, 8) == 8 * qz.QBLOCK


class TestWireDtypePlumbing:
    def test_wire_dtype_resolution_and_bytes(self):
        assert WIRE_DTYPES == ("f32", "bf16", "int8", "int8_ring")
        assert comm_dtype_of("int8") == "int8"
        assert comm_dtype_of("int8_ring") == "int8_ring"
        assert wire_dtype_name(comm_dtype_of("int8")) == "int8"
        assert wire_dtype_name(comm_dtype_of("int8_ring")) == "int8_ring"
        assert wire_dtype_name(comm_dtype_of("bf16")) == "bf16"
        assert wire_dtype_name(comm_dtype_of(None)) == "f32"
        ratio = (wire_bytes_per_elem("int8")
                 / wire_bytes_per_elem(jnp.bfloat16))
        assert ratio <= 0.55           # the ISSUE acceptance bound

    def test_report_wire_literal_pinned(self):
        """telemetry/report.py carries a jax-free literal mirror of
        WIRE_DTYPES; pin it (same rule as the STRATEGIES mirror)."""
        import inspect

        from dtf_tpu.telemetry import report
        assert ('("f32", "bf16", "int8", "int8_ring")'
                in inspect.getsource(report.render))

    def test_config_accepts_int8_and_rounding(self):
        from dtf_tpu.config import TrainConfig
        TrainConfig(grad_comm_dtype="int8", quant_rounding="stochastic")
        with pytest.raises(ValueError, match="quant_rounding"):
            TrainConfig(quant_rounding="up")
        with pytest.raises(ValueError, match="grad_comm_dtype"):
            TrainConfig(grad_comm_dtype="int4")
        # stochastic without the int8 wire would be silently inert — the
        # bf16/f32 wires have no quantizer — so it is rejected loud.
        with pytest.raises(ValueError, match="stochastic"):
            TrainConfig(grad_comm_dtype="bf16",
                        quant_rounding="stochastic")
        with pytest.raises(ValueError, match="stochastic"):
            TrainConfig(quant_rounding="stochastic")

    def test_engine_wire_stats_ratios(self, mesh8):
        """comm_stats at equal bucket layout: int8 wire <= 0.55x bf16 and
        <= 0.28x f32 (the ~2x / ~4x claims with chunk-padding slack)."""
        opt = optim.adam(1e-3)
        stats = {}
        layouts = {}
        for cd in (None, "bf16", "int8"):
            eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1,
                              comm_dtype=cd)
            stats[cd] = eng.comm_stats(1)
            layouts[cd] = eng.layout.padded
        assert layouts[None] == layouts["bf16"] == layouts["int8"]
        assert (stats["int8"]["wire_bytes"]
                <= 0.55 * stats["bf16"]["wire_bytes"])
        assert (stats["int8"]["wire_bytes"]
                <= 0.28 * stats[None]["wire_bytes"])
        # grad_sync_bytes adds the f32 param all-gather for all three
        for cd in stats:
            assert (stats[cd]["grad_sync_bytes"]
                    > stats[cd]["wire_bytes"])


class TestInt8WireTraining:
    @pytest.mark.parametrize("strat", ["dense", "zero1"])
    def test_trajectory_close_to_exact(self, mesh8, strat):
        """3 steps of MNIST, int8 wire vs exact f32: params within the
        quantization tolerance (same bound class as the bf16 wire test
        in test_grad_sync.py)."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for cd in (None, "int8"):
            opt = optim.adam(1e-3)
            eng = (make_engine(strat, opt, mesh8, bucket_mb=0.1,
                               comm_dtype=cd)
                   if strat != "dense" else None)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8,
                                   mode="explicit", donate=False,
                                   grad_sync=eng,
                                   grad_comm_dtype=cd if eng is None
                                   else None)
            b = put_global_batch(mesh8, batch)
            for i in range(3):
                state, m = step(state, b, jax.random.key(i))
            out[cd] = (state["params"], m)
        for la, lb in zip(jax.tree_util.tree_leaves(out[None][0]),
                          jax.tree_util.tree_leaves(out["int8"][0])):
            # Wider than the bf16-wire bound: 8-bit block scales are a
            # coarser lattice, and Adam's rsqrt(v) amplifies noise on
            # near-zero entries.
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=5e-2, atol=5e-3)
        assert 0 < float(out["int8"][1]["quant_error"]) < 0.1
        assert "quant_error" not in out[None][1]

    def test_stochastic_rounding_reproducible_trajectory(self, mesh8):
        """Same seed -> bitwise-identical params across two stochastic
        int8 runs (draws derive from the step rng); a different seed
        moves them."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")

        def train(rng_seed):
            opt = optim.adam(1e-3)
            eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1,
                              comm_dtype="int8",
                              quant_rounding="stochastic")
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8,
                                   mode="explicit", donate=False,
                                   grad_sync=eng,
                                   quant_rounding="stochastic")
            b = put_global_batch(mesh8, batch)
            for i in range(2):
                state, _ = step(state, b, jax.random.key(i + rng_seed))
            return state["params"]

        a, b_, c = train(0), train(0), train(100)
        for la, lb in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(b_)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        deltas = [float(jnp.abs(x - y).max()) for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(c))]
        assert max(deltas) > 0

    def test_guard_skips_poisoned_step_on_int8_wire(self, mesh8):
        """The satellite's guard hook: NaNs in the batch under the int8
        wire — the PRE-sync isfinite verdict skips the step (params and
        sharded opt state untouched) even though the wire itself would
        have decoded the NaN block to NaN anyway."""
        opt = optim.adam(1e-3)
        model = MnistMLP(init_scale="fan_in")
        eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1,
                          comm_dtype="int8")
        state = init_state(model, opt, seed=1, mesh=mesh8, guard=True,
                           grad_sync=eng)
        step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                               donate=False, guard=True, grad_sync=eng)
        x, y = mlp_batch()
        x[3, 5] = np.nan
        new, m = step(state, put_global_batch(mesh8, (x, y)),
                      jax.random.key(0))
        assert int(m["nonfinite"]) == 1
        assert int(new["skipped"]) == 1
        for la, lb in zip(jax.tree_util.tree_leaves(state["params"]),
                          jax.tree_util.tree_leaves(new["params"])):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_overlap_int8_composes_with_grad_accum(self, mesh8):
        """zero1_overlap + int8 wire + grad_accum: per-microbatch
        quantized scatters accumulate to a trajectory near the exact
        accumulated step."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for cd in (None, "int8"):
            opt = optim.adam(1e-3)
            eng = make_engine("zero1_overlap", opt, mesh8, bucket_mb=0.1,
                              comm_dtype=cd)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8,
                                   mode="explicit", donate=False,
                                   grad_sync=eng, grad_accum=4)
            state, m = step(state, put_global_batch(mesh8, batch),
                            jax.random.key(0))
            out[cd] = (state["params"], m)
        for la, lb in zip(jax.tree_util.tree_leaves(out[None][0]),
                          jax.tree_util.tree_leaves(out["int8"][0])):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=5e-2, atol=5e-3)
        assert float(out["int8"][1]["quant_error"]) > 0


class TestLowPrecisionMatmul:
    def _xw(self, m=24, k=48, n=32, seed=0):
        rng = np.random.default_rng(seed)
        return (jnp.asarray(rng.normal(size=(4, m, k)), jnp.float32),
                jnp.asarray(rng.normal(size=(k, n)), jnp.float32))

    @pytest.mark.parametrize("dt,tol", [("bf16", 0.02), ("int8", 0.03),
                                        ("fp8", 0.08)])
    def test_forward_close_to_fp32(self, dt, tol):
        from dtf_tpu.nn.lowp import lowp_matmul
        x, w = self._xw()
        y0, y = x @ w, lowp_matmul(x, w, dt)
        rel = float(jnp.linalg.norm(y - y0) / jnp.linalg.norm(y0))
        assert rel < tol

    def test_per_channel_scale_tames_outlier_column(self):
        """One huge output channel must not destroy the others' precision
        — the reason the scales are per channel, not per tensor."""
        from dtf_tpu.nn.lowp import lowp_matmul
        x, w = self._xw()
        w = w.at[:, 3].mul(1000.0)
        y0, y = x @ w, lowp_matmul(x, w, "int8")
        others = jnp.delete(jnp.arange(w.shape[1]), 3)
        rel = float(jnp.linalg.norm(y[..., others] - y0[..., others])
                    / jnp.linalg.norm(y0[..., others]))
        assert rel < 0.03

    @pytest.mark.parametrize("dt", ["int8", "fp8"])
    def test_straight_through_gradients(self, dt):
        """round() has zero gradient; the STE backward must deliver the
        fp32 matmul's gradients (else training silently stalls)."""
        from dtf_tpu.nn.lowp import lowp_matmul
        x, w = self._xw()
        g = jax.grad(lambda w_: jnp.sum(lowp_matmul(x, w_, dt) ** 2))(w)
        g0 = jax.grad(lambda w_: jnp.sum((x @ w_) ** 2))(w)
        rel = float(jnp.linalg.norm(g - g0) / jnp.linalg.norm(g0))
        assert rel < 0.05
        assert float(jnp.abs(g).max()) > 0

    def test_unknown_dtype_rejected(self):
        from dtf_tpu.nn.lowp import lowp_matmul
        with pytest.raises(ValueError, match="matmul_dtype"):
            lowp_matmul(jnp.ones((2, 4)), jnp.ones((4, 2)), "int4")


class TestGPTMatmulDtype:
    @pytest.mark.parametrize("dt", ["int8", "fp8"])
    def test_tiny_gpt_trains_and_loss_drops(self, dt):
        from dtf_tpu.data.datasets import synthetic_text
        from dtf_tpu.models.gpt import GPT, GPTConfig

        model = GPT(GPTConfig.tiny(matmul_dtype=dt))
        params = model.init(jax.random.key(0))
        toks = jnp.asarray(synthetic_text(16, 64, 128, seed=3))
        opt = optim.adam(1e-3)
        state = opt.init(params)

        @jax.jit
        def step(p, s):
            (l, _), g = jax.value_and_grad(
                lambda p_: model.loss(p_, {"tokens": toks}),
                has_aux=True)(p)
            u, s = opt.update(g, s, p)
            return optim.apply_updates(p, u), s, l

        losses = []
        for _ in range(8):
            params, state, l = step(params, state)
            losses.append(float(l))
        assert losses[-1] < losses[0] - 0.05   # actually learning

    def test_logits_close_to_fp32_forward(self):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        toks = jnp.asarray(np.random.default_rng(6).integers(
            0, 128, (2, 32)), jnp.int32)
        m0 = GPT(GPTConfig.tiny())
        p = m0.init(jax.random.key(0))
        l0 = m0.apply(p, toks)
        # fp8 e4m3 carries 3 mantissa bits vs int8's ~7 — its lattice is
        # coarser, so its directional bound is looser by construction.
        for dt, bound in (("int8", 0.998), ("fp8", 0.98)):
            lq = GPT(GPTConfig.tiny(matmul_dtype=dt)).apply(p, toks)
            cos = jnp.sum(l0 * lq, -1) / (
                jnp.linalg.norm(l0, axis=-1) * jnp.linalg.norm(lq, axis=-1))
            assert float(cos.min()) > bound, (dt, float(cos.min()))

    def test_fused_block_composition_contract(self):
        """int8 now COMPOSES with fused_block (the fused kernels grew an
        int8 operand path — tests/test_block_kernel.py::TestInt8Fused
        pins parity); bf16/fp8 still conflict, loudly."""
        from dtf_tpu.models.gpt import GPT, GPTConfig
        GPT(GPTConfig.tiny(matmul_dtype="int8", fused_block=True))
        for md in ("bf16", "fp8"):
            with pytest.raises(ValueError, match="matmul_dtype"):
                GPT(GPTConfig.tiny(matmul_dtype=md, fused_block=True))

    def test_bad_dtype_rejected_at_construction(self):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        with pytest.raises(ValueError, match="matmul_dtype"):
            GPT(GPTConfig.tiny(matmul_dtype="int4"))


class TestTrajectoryHarness:
    def test_traj_run_within_envelope_int8_wire(self, mesh8):
        """The quality gate itself: tiny-GPT LM, int8 wire vs fp32,
        within the pinned envelope on the 8-device sim mesh.  (mesh8
        fixture guarantees the 8 simulated devices exist; traj_run
        builds its own data mesh.)"""
        from dtf_tpu.bench.int8_quality import TRAJ_ENVELOPE, traj_run

        r = traj_run(steps=6, batch=16, seq=32, grad_sync="zero1",
                     grad_comm_dtype="int8")
        assert r["data_axis"] == 8
        assert len(r["loss_fp32"]) == len(r["loss_quant"]) == 6
        assert r["within_envelope"], (r["max_rel_dev"], r["final_rel_dev"])
        assert r["envelope"] == TRAJ_ENVELOPE
        assert r["quant_error_rms"] > 0

    def test_traj_run_matmul_dtype_leg(self, mesh8):
        from dtf_tpu.bench.int8_quality import traj_run

        r = traj_run(steps=4, batch=16, seq=32, grad_sync="dense",
                     grad_comm_dtype=None, matmul_dtype="int8")
        assert r["within_envelope"], (r["max_rel_dev"], r["final_rel_dev"])
        assert r["quant_error_rms"] is None   # no wire quantization


class TestRingReduceScatter:
    """EQuARX-style per-hop quantized ring reduce-scatter (ISSUE 19):
    parity vs the exact mean within the accumulated per-hop bound, the
    (n-1)-chunk wire win, hop accounting, and seeded reproducibility."""

    # The shard_map compiles below are ~10-20s each on this 1-core rig;
    # the heavy parity/error-ladder legs ride the full-suite run ("slow
    # or not slow") while tier-1 keeps the cheap accounting + 3-step
    # trajectory coverage.
    @pytest.mark.slow
    def test_ring_matches_dense_mean_within_per_hop_bound(self, mesh8):
        n = 8
        length = n * 1000              # NOT a QBLOCK multiple: chunk pad
        rng = np.random.default_rng(7)
        locals_ = rng.normal(size=(n, length)).astype(np.float32)
        dense_mean = locals_.mean(axis=0)

        def f(vs):
            shard = qz.ring_reduce_scatter_quantized(vs[0] * (1.0 / n),
                                                     "data")
            return qz.all_gather_quantized(shard, "data")[None]

        out = np.asarray(shard_map_fn(
            f, mesh=mesh8, in_specs=P("data"),
            out_specs=P("data"))(locals_))
        for row in out:                # replica-identical by construction
            np.testing.assert_array_equal(row, out[0])
        # Each of the n-1 hops re-quantizes the partial sum (magnitude
        # <= full sum), plus one rounding on the gather leg.
        tol = np.abs(locals_).max() / 127.0 * n
        np.testing.assert_allclose(out[0], dense_mean, atol=tol)

    @pytest.mark.slow
    def test_ring_shard_matches_oneshot_owner_contract(self, mesh8):
        """Rank me owns chunk me — the SAME tiled contract as the
        one-shot reduce_scatter_quantized, so the two are drop-in
        interchangeable inside the engine's bucket layout."""
        n = 8
        length = n * qz.QBLOCK
        rng = np.random.default_rng(11)
        locals_ = rng.normal(size=(n, length)).astype(np.float32)

        def f(vs):
            ring = qz.ring_reduce_scatter_quantized(vs[0], "data")
            one = qz.reduce_scatter_quantized(vs[0], "data")
            return ring[None], one[None]

        ring, one = shard_map_fn(
            f, mesh=mesh8, in_specs=P("data"),
            out_specs=(P("data"), P("data")))(locals_)
        exact = locals_.sum(axis=0).reshape(n, -1)
        tol = np.abs(locals_.sum(axis=0)).max() / 127.0 * n
        np.testing.assert_allclose(np.asarray(ring).reshape(n, -1),
                                   exact, atol=tol)
        np.testing.assert_allclose(np.asarray(one).reshape(n, -1),
                                   exact, atol=tol)

    @pytest.mark.slow
    def test_ring_per_hop_error_accumulates(self, mesh8):
        """return_error books one requant error per hop: the ring's
        accumulated error exceeds the one-shot single-rounding error on
        the same input (both positive)."""
        n = 8
        length = n * qz.QBLOCK
        rng = np.random.default_rng(13)
        locals_ = rng.normal(size=(n, length)).astype(np.float32)

        def f(vs):
            _, e_ring = qz.ring_reduce_scatter_quantized(
                vs[0], "data", return_error=True)
            _, e_one = qz.reduce_scatter_quantized(
                vs[0], "data", return_error=True)
            return e_ring[None], e_one[None]

        e_ring, e_one = shard_map_fn(
            f, mesh=mesh8, in_specs=P("data"),
            out_specs=(P("data"), P("data")))(locals_)
        r, o = np.asarray(e_ring)[0], np.asarray(e_one)[0]
        assert r[0] > 0 and o[0] > 0
        assert r[1] > 0                      # payload power booked
        assert r[0] > o[0]                   # n-1 roundings vs 1

    def test_ring_wire_elems_accounting(self):
        # 8 chunks of 1000 -> each pads to 4*QBLOCK=1024; the ring ships
        # n-1 of them per device instead of n.
        assert qz.ring_wire_elems(8000, 8) == 7 * 1024
        assert qz.ring_wire_elems(8000, 8) < qz.wire_elems(8000, 8)
        assert qz.ring_wire_elems(8 * qz.QBLOCK, 8) == 7 * qz.QBLOCK
        # degenerate single shard: nothing on the wire
        assert qz.ring_wire_elems(1000, 1) == 0

    def test_engine_hop_count_and_wire_win(self, mesh8):
        """comm_stats: int8_ring books n-1 hops and strictly fewer
        scatter-leg wire bytes than one-shot int8 at the same layout."""
        opt = optim.adam(1e-3)
        ring = make_engine("zero1", opt, mesh8, bucket_mb=0.1,
                           comm_dtype="int8_ring")
        one = make_engine("zero1", opt, mesh8, bucket_mb=0.1,
                          comm_dtype="int8")
        s_ring, s_one = ring.comm_stats(1), one.comm_stats(1)
        assert s_ring["hops"] == 7 and s_one["hops"] == 1
        assert s_ring["wire_bytes"] < s_one["wire_bytes"]
        np.testing.assert_allclose(s_ring["wire_bytes"],
                                   s_one["wire_bytes"] * 7 / 8)

    @pytest.mark.slow
    @pytest.mark.parametrize("strat", ["dense", "zero1"])
    def test_ring_trajectory_close_to_exact(self, mesh8, strat):
        """3 MNIST steps on the int8_ring wire vs exact f32: params
        within the (wider, per-hop) quantization tolerance, quant_error
        aux populated."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")
        out = {}
        for cd in (None, "int8_ring"):
            opt = optim.adam(1e-3)
            eng = (make_engine(strat, opt, mesh8, bucket_mb=0.1,
                               comm_dtype=cd)
                   if strat != "dense" else None)
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8,
                                   mode="explicit", donate=False,
                                   grad_sync=eng,
                                   grad_comm_dtype=cd if eng is None
                                   else None)
            b = put_global_batch(mesh8, batch)
            for i in range(3):
                state, m = step(state, b, jax.random.key(i))
            out[cd] = (state["params"], m)
        for la, lb in zip(jax.tree_util.tree_leaves(out[None][0]),
                          jax.tree_util.tree_leaves(out["int8_ring"][0])):
            # Wider than the one-shot int8 bound: 7 requantizations on
            # the scatter path instead of 1.
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=8e-2, atol=8e-3)
        assert 0 < float(out["int8_ring"][1]["quant_error"]) < 0.5
        assert "quant_error" not in out[None][1]

    @pytest.mark.slow
    def test_ring_stochastic_seeded_reproducible(self, mesh8):
        """Stochastic per-hop rounding: same step rng -> bitwise-equal
        params across runs (hop draws fold_in the hop index); a
        different seed moves them."""
        batch = mlp_batch()
        model = MnistMLP(init_scale="fan_in")

        def train(rng_seed):
            opt = optim.adam(1e-3)
            eng = make_engine("zero1", opt, mesh8, bucket_mb=0.1,
                              comm_dtype="int8_ring",
                              quant_rounding="stochastic")
            state = init_state(model, opt, seed=1, mesh=mesh8,
                               grad_sync=eng)
            step = make_train_step(model.loss, opt, mesh8,
                                   mode="explicit", donate=False,
                                   grad_sync=eng,
                                   quant_rounding="stochastic")
            b = put_global_batch(mesh8, batch)
            for i in range(2):
                state, _ = step(state, b, jax.random.key(i + rng_seed))
            return state["params"]

        a, b_, c = train(0), train(0), train(100)
        for la, lb in zip(jax.tree_util.tree_leaves(a),
                          jax.tree_util.tree_leaves(b_)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        deltas = [float(jnp.abs(x - y).max()) for x, y in zip(
            jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(c))]
        assert max(deltas) > 0

    @pytest.mark.slow
    def test_traj_run_int8_ring_within_envelope(self, mesh8):
        """int8_quality --trajectory on the ring wire: the per-hop
        requant ladder stays inside the SAME committed envelope as the
        one-shot wire."""
        from dtf_tpu.bench.int8_quality import TRAJ_ENVELOPE, traj_run

        r = traj_run(steps=6, batch=16, seq=32, grad_sync="zero1",
                     grad_comm_dtype="int8_ring")
        assert r["within_envelope"], (r["max_rel_dev"], r["final_rel_dev"])
        assert r["envelope"] == TRAJ_ENVELOPE
        assert r["quant_error_rms"] > 0
