"""2-process restore_robust driver (spawned by tests/test_multiprocess.py).

Exercises the multi-host branch of CheckpointManager.restore_robust — the
coordinator-broadcast step pick and the symmetric per-attempt agreement —
against a corrupted latest checkpoint on a shared directory: both processes
must fall back to the SAME older step (a divergent choice would deadlock
the collective restore; this driver would then time out in the rig).

Usage: _mp_restore_robust.py <task_index> <port> <ckpt_dir>
"""

import sys


def main(task: int, port: int, ckpt_dir: str) -> None:
    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig

    cluster = bootstrap(ClusterConfig(
        task_index=task, coordinator_address=f"localhost:{port}",
        num_processes=2, mesh="data=-1"))

    import jax
    import numpy as np
    from jax.experimental import multihost_utils

    from dtf_tpu import optim
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.train.checkpoint import CheckpointManager
    from dtf_tpu.train.trainer import init_state

    mesh = cluster.mesh
    model = MnistMLP(init_scale="fan_in")
    opt = optim.sgd(0.1)
    s10 = init_state(model, opt, seed=1, mesh=mesh, guard=True)
    s20 = init_state(model, opt, seed=2, mesh=mesh, guard=True)

    mgr = CheckpointManager(ckpt_dir, async_save=False)
    mgr.save(10, s10, force=True)
    mgr.save(20, s20, force=True)
    mgr.wait()

    if jax.process_index() == 0:
        from dtf_tpu.resilience.chaos import corrupt_tree
        corrupt_tree(mgr.step_dir(20), seed=3)
    multihost_utils.sync_global_devices("corrupted-latest")

    template = init_state(model, opt, seed=3, mesh=mesh, guard=True)
    restored, step = mgr.restore_robust(template)
    assert step == 10, f"expected fallback to step 10, got {step}"
    got = np.asarray(restored["params"]["l1"]["w"].addressable_data(0))
    want = np.asarray(s10["params"]["l1"]["w"].addressable_data(0))
    assert np.array_equal(got, want), "restored values != step-10 values"
    mgr.close()
    print(f"RESTORE_ROBUST_MP_OK step {step}", flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]), int(sys.argv[2]), sys.argv[3])
