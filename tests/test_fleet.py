"""Fleet observability plane (dtf_tpu/telemetry/fleet.py, ISSUE 12).

Fast units: clock-offset recovery under an injected skew, skew/blame
attribution math (resync vs observational cost), the dual mesh
transports, the /fleetz endpoint's consistent-cut contract under
concurrent scrapes (HTTP layer), fleet gates in check_gates (absence =
fail + falsifiability), offset-rebased trace export, and the reqtrace
readers over a merged multi-host stream.

Slow (TestFleetTwoProcess, conftest slow-list): a REAL 2-process run
through tests/_mp_fleet.py with an injected ``slow_host`` straggler —
blame must land on exactly the injected host (>= 80%), the measured
drift must match the injected per-step delay within tolerance, the
merged trace must carry both hosts, and the report gates must pass sane
thresholds and FAIL absurd ones.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from dtf_tpu.telemetry import fleet

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_events(n_barriers=12, offsets=(0.0, 3.5), lateness=(0.0, 0.2),
               wait=True, kind="log"):
    """Synthetic fleet/sync events: hosts release together at true time
    ``1000 + 10 b``; host i arrives ``lateness[i]`` late relative to the
    earliest and stamps everything on a clock shifted by ``offsets[i]``."""
    ev = []
    for b in range(n_barriers):
        release = 1000.0 + 10.0 * b
        for p, (off, late) in enumerate(zip(offsets, lateness)):
            arrive = release - 1.0 + late
            ev.append({"pid": p, "barrier": fleet.barrier_id(kind, b),
                       "kind": kind, "step": b,
                       "arrive_s": arrive + off,
                       "wait_s": (release - arrive) if wait else 0.0})
    return ev


class TestOffsets:
    def test_recovers_injected_skew(self):
        """3.5 s of injected clock skew on host 1 recovers to within a
        millisecond from release-stamp medians."""
        off = fleet.estimate_offsets(_mk_events(offsets=(0.0, 3.5)))
        assert off[0] == 0.0
        assert abs(off[1] - 3.5) < 1e-3

    def test_three_hosts_mixed_offsets(self):
        off = fleet.estimate_offsets(
            _mk_events(offsets=(0.0, -1.25, 0.75),
                       lateness=(0.0, 0.1, 0.3)))
        assert abs(off[1] + 1.25) < 1e-3 and abs(off[2] - 0.75) < 1e-3

    def test_arrival_skew_does_not_pollute_offset(self):
        """A persistent straggler (large arrival lateness) must NOT read
        as clock offset — offsets come from release stamps only."""
        off = fleet.estimate_offsets(
            _mk_events(offsets=(0.0, 0.0), lateness=(0.0, 0.8)))
        assert abs(off[1]) < 1e-3

    def test_no_release_info_defaults_zero(self):
        """Observational (file-mesh) events carry no wait: no clock edge
        to estimate from, so offsets default to 0 — correct on the one
        machine such rigs run on, and flagged by fleet_report."""
        ev = _mk_events(offsets=(0.0, 2.0), wait=False)
        off = fleet.estimate_offsets(ev)
        assert off == {0: 0.0, 1: 0.0}
        rep = fleet.fleet_report(records=[
            {"name": "fleet/sync", "ph": "X", "pid": e["pid"],
             "ts": e["arrive_s"] * 1e6, "dur": 0.0,
             "args": {"barrier": e["barrier"], "kind": e["kind"],
                      "step": e["step"], "host": e["pid"]}}
            for e in ev])
        assert rep["offset_estimated"]["1"] is False

    def test_empty(self):
        assert fleet.estimate_offsets([]) == {}


class TestAttribution:
    def test_blame_lands_on_straggler_despite_clock_skew(self):
        """Host 1 arrives 0.2 s late at every barrier while carrying a
        3.5 s clock offset — attribution must blame it 100% with the
        skew measured at ~200 ms, not at seconds."""
        ev = _mk_events(offsets=(0.0, 3.5), lateness=(0.0, 0.2))
        att = fleet.attribute(ev, fleet.estimate_offsets(ev))
        assert att["per_host"]["1"]["blame_frac"] == 1.0
        assert abs(att["skew_ms_p50"] - 200.0) < 1.0

    def test_uncorrected_offset_would_flip_blame(self):
        """The falsifiability twin: WITHOUT offset correction the 3.5 s
        clock skew dominates and the verdict is wrong — proving the
        correction is load-bearing."""
        ev = _mk_events(offsets=(-3.5, 0.0), lateness=(0.2, 0.0))
        att_raw = fleet.attribute(ev, None)
        att_fixed = fleet.attribute(ev, fleet.estimate_offsets(ev))
        assert att_raw["per_host"]["1"]["blame_frac"] == 1.0   # wrong host
        assert att_fixed["per_host"]["0"]["blame_frac"] == 1.0

    def test_resync_cost_sums_margins(self):
        """Resyncing barriers (wait-bearing): each window pays the last
        host's margin afresh, so cost = n_barriers * margin."""
        ev = _mk_events(n_barriers=10, offsets=(0.0, 0.0),
                        lateness=(0.0, 0.2), wait=True)
        att = fleet.attribute(ev, {})
        assert abs(att["per_host"]["1"]["lateness_s"] - 2.0) < 1e-6

    def test_observational_cost_is_incremental(self):
        """Observational barriers carry ACCUMULATED lag: a host drifting
        40 ms/barrier to 400 ms total must book ~0.4 s of cost, not the
        ~2.2 s a naive margin sum would claim."""
        ev = []
        for b in range(10):
            t0 = 1000.0 + 10.0 * b
            ev.append({"pid": 0, "barrier": fleet.barrier_id("log", b),
                       "kind": "log", "step": b, "arrive_s": t0,
                       "wait_s": 0.0})
            ev.append({"pid": 1, "barrier": fleet.barrier_id("log", b),
                       "kind": "log", "step": b,
                       "arrive_s": t0 + 0.04 * (b + 1), "wait_s": 0.0})
        att = fleet.attribute(ev, {})
        assert abs(att["per_host"]["1"]["lateness_s"] - 0.4) < 1e-6
        assert abs(att["per_host"]["1"]["drift_ms_per_step"] - 40.0) < 1.0

    def test_single_host_barriers_skipped(self):
        ev = [{"pid": 0, "barrier": "log_00000001", "kind": "log",
               "step": 1, "arrive_s": 1.0, "wait_s": 0.0}]
        assert fleet.attribute(ev, {}) is None

    def test_drift_reads_injected_delay(self):
        """Drift slope ~= the per-step delay a persistent straggler
        injects (the measurement the 2-process A/B keys on)."""
        ev = []
        for b, step in enumerate(range(2, 42, 2)):    # log every 2 steps
            t0 = 1000.0 + 0.1 * step
            for p, extra in ((0, 0.0), (1, 0.04 * step)):
                ev.append({"pid": p,
                           "barrier": fleet.barrier_id("log", step),
                           "kind": "log", "step": step,
                           "arrive_s": t0 + extra, "wait_s": 0.0})
        att = fleet.attribute(ev, {})
        assert abs(att["per_host"]["1"]["drift_ms_per_step"] - 40.0) < 0.5


class TestSplitUnix:
    def test_round_trip_survives_f32_wire(self):
        """The allgather ride's precision contract: jax's x64-off
        canonicalization forces the wire to f32, whose spacing at
        current epoch is 128-256 s — the split (hi, lo) pair must
        reconstruct epoch stamps to well under a millisecond AFTER an
        f32 round-trip, or multi-host skew attribution is garbage."""
        import numpy as np
        base = 1.7e9
        for dt in (0.0, 0.001, 0.0404, 63.999, 127.5):
            t = base + dt
            hi, lo = fleet.split_unix(t)
            # the wire: both halves quantized to f32
            hi32, lo32 = float(np.float32(hi)), float(np.float32(lo))
            back = fleet.merge_unix(hi32, lo32)
            assert abs(back - t) < 1e-4, (t, back)
        # and the naive single-f32 wire really would destroy it
        assert abs(float(np.float32(base + 40.0))
                   - float(np.float32(base))) in (0.0, 128.0, 256.0)

    def test_deltas_preserved(self):
        """Two hosts 40 ms apart stay 40 ms apart through the split
        wire (the quantity blame ranking consumes)."""
        import numpy as np
        a, b = 1.7e9 + 12.345678, 1.7e9 + 12.385678
        enc = [tuple(float(np.float32(x)) for x in fleet.split_unix(t))
               for t in (a, b)]
        da = fleet.merge_unix(*enc[1]) - fleet.merge_unix(*enc[0])
        assert abs(da - 0.04) < 1e-4


class TestMesh:
    def test_file_mesh_round_trip(self, tmp_path):
        m0 = fleet.FileFleetMesh(str(tmp_path), 0)
        m1 = fleet.FileFleetMesh(str(tmp_path), 1)
        m0.append_sync({"barrier": "log_00000002", "kind": "log",
                        "step": 2, "p": 0, "t": 10.0, "w": 0.0})
        m1.append_sync({"barrier": "log_00000002", "kind": "log",
                        "step": 2, "p": 1, "t": 10.5, "w": 0.0})
        m1.publish_host({"process": 1, "rev": 3, "rev_echo": 3})
        syncs = m0.read_syncs()
        assert syncs[0][0]["t"] == 10.0 and syncs[1][0]["t"] == 10.5
        assert m0.read_hosts()[1]["rev"] == 3

    def test_file_mesh_torn_tail_dropped(self, tmp_path):
        m = fleet.FileFleetMesh(str(tmp_path), 0)
        m.append_sync({"barrier": "log_00000002", "kind": "log",
                       "step": 2, "p": 0, "t": 10.0, "w": 0.0})
        with open(os.path.join(str(tmp_path),
                               "fleet_sync_p0.jsonl"), "a") as f:
            f.write('{"barrier": "log_0000')       # hard-kill torn line
        assert len(m.read_syncs()[0]) == 1

    def test_file_mesh_rendezvous(self, tmp_path):
        m0 = fleet.FileFleetMesh(str(tmp_path), 0)
        m1 = fleet.FileFleetMesh(str(tmp_path), 1)
        m0.mark_ready()
        assert m0.ready_count() == 1
        m1.mark_ready()
        assert m0.ready_count() == 2

    def test_tcp_mesh_round_trip(self):
        coord = fleet.TcpFleetMesh("127.0.0.1:0", 0, is_coordinator=True)
        try:
            addr = f"127.0.0.1:{coord._server.address[1]}"
            client = fleet.TcpFleetMesh(addr, 1, is_coordinator=False)
            client.append_sync({"barrier": "log_00000002", "kind": "log",
                                "step": 2, "p": 1, "t": 10.5, "w": 0.1})
            client.publish_host({"process": 1, "rev": 7, "rev_echo": 7})
            client.mark_ready()
            coord.mark_ready()
            deadline = time.time() + 5
            while time.time() < deadline:
                if coord.read_hosts().get(1, {}).get("rev") == 7:
                    break
                time.sleep(0.05)
            assert coord.read_syncs()[1][0]["t"] == 10.5
            assert coord.read_hosts()[1]["rev_echo"] == 7
            assert coord.ready_count() == 2
            # clients observe nothing (the coordinator holds the books)
            assert client.read_hosts() == {}
        finally:
            coord.close()

    def test_tcp_mesh_malformed_line_survives(self):
        coord = fleet.TcpFleetMesh("127.0.0.1:0", 0, is_coordinator=True)
        try:
            import socket as _socket
            with _socket.create_connection(coord._server.address,
                                           timeout=2) as conn:
                conn.sendall(b"GET / HTTP/1.1\r\n")
                reply = conn.makefile("r").readline()
            assert reply.startswith("err")
            # the sink still works afterwards
            client = fleet.TcpFleetMesh(
                f"127.0.0.1:{coord._server.address[1]}", 1, False)
            client.publish_host({"process": 1, "rev": 1, "rev_echo": 1})
            assert coord.read_hosts()[1]["rev"] == 1
        finally:
            coord.close()

    def test_make_fleet_mesh_dispatch(self, tmp_path):
        m = fleet.make_fleet_mesh(str(tmp_path / "d"), 0, True)
        assert isinstance(m, fleet.FileFleetMesh)
        t = fleet.make_fleet_mesh("tcp://127.0.0.1:0", 0, True)
        try:
            assert isinstance(t, fleet.TcpFleetMesh)
        finally:
            t.close()


class TestPlane:
    def test_note_sync_emits_span_and_mesh(self, tmp_path):
        from dtf_tpu import telemetry as tel
        tel.configure(str(tmp_path / "logs"), 0)
        try:
            plane = fleet.FleetPlane(
                fleet.FileFleetMesh(str(tmp_path / "mesh"), 0), 0, 2,
                spans_dir=str(tmp_path / "logs"))
            plane.note_sync("log", 4, arrival_unix=100.0, wait_s=0.25)
            tel.get_tracer().flush()
            from dtf_tpu.telemetry.spans import read_spans
            recs = read_spans(str(tmp_path / "logs" / "spans.p0.jsonl"))
            ev = fleet.sync_events(recs)
            assert ev == [{"pid": 0, "barrier": "log_00000004",
                           "kind": "log", "step": 4, "arrive_s": 100.0,
                           "wait_s": 0.25}]
            assert plane.mesh.read_syncs()[0][0]["barrier"] == \
                "log_00000004"
        finally:
            tel.configure(None)

    def test_coordinator_books_completed_barriers(self, tmp_path):
        """The coordinator ingests a barrier exactly once, only when all
        nproc hosts have reached it, and blames the last arrival."""
        from dtf_tpu.telemetry import registry as _registry
        mesh_dir = str(tmp_path / "mesh")
        p0 = fleet.FleetPlane(fleet.FileFleetMesh(mesh_dir, 0), 0, 2)
        p1 = fleet.FleetPlane(fleet.FileFleetMesh(mesh_dir, 1), 1, 2)
        reg = _registry.get_registry()
        before = reg.counter("fleet/barriers_total").value
        p0.note_sync("log", 2, arrival_unix=10.0)
        assert reg.counter("fleet/barriers_total").value == before  # half
        p1.note_sync("log", 2, arrival_unix=10.3)
        p0.note_sync("log", 4, arrival_unix=20.0)     # triggers ingest
        assert reg.counter("fleet/barriers_total").value == before + 1
        assert p0._blame == {1: 1}
        p0.note_sync("ckpt", 5, arrival_unix=30.0)    # re-ingest: no dup
        assert reg.counter("fleet/barriers_total").value == before + 1

    def test_live_booking_is_offset_corrected(self, tmp_path):
        """THE live-plane twin of the post-hoc correction: host 1's
        clock runs 2 s ahead but host 0 is the true straggler (arrives
        0.2 s late at every release-bearing barrier).  Raw ranking
        would blame host 1 at every barrier; the coordinator must fold
        the release stamps into a running offset and blame host 0."""
        mesh_dir = str(tmp_path / "mesh")
        p0 = fleet.FleetPlane(fleet.FileFleetMesh(mesh_dir, 0), 0, 2)
        m1 = fleet.FileFleetMesh(mesh_dir, 1)
        off1 = 2.0
        for b in range(8):
            release = 1000.0 + 10.0 * b
            # host 0 (coordinator, true clock): arrives late, waits 0.1
            p0.note_sync("log", b, arrival_unix=release - 0.1,
                         wait_s=0.1)
            # host 1 (clock +2 s): arrives early, waits 0.3
            m1.append_sync({"barrier": fleet.barrier_id("log", b),
                            "kind": "log", "step": b, "p": 1,
                            "t": release - 0.3 + off1, "w": 0.3})
        p0.note_sync("log", 99, arrival_unix=2000.0)   # sweep trigger
        doc = p0.fleetz()
        att = doc["attribution"]
        assert att["barriers"] >= 7
        # the first barrier books before any offset sample exists (its
        # own stamps are what seed the estimate), so host 1 may eat one
        # blame; every later barrier must blame the true straggler
        assert att["blame"].get("0", 0) >= att["barriers"] - 1, att
        assert abs(float(att["offsets_s"]["1"]) - off1) < 1e-6

    def test_ingest_bounds_booked_and_pending(self, tmp_path, monkeypatch):
        """The coordinator's ledgers stay bounded: booked-barrier dedup
        ids evict oldest-first, and a dead host's incomplete barriers
        are pruned instead of piling up forever."""
        monkeypatch.setattr(fleet, "_BOOKED_KEEP", 8)
        monkeypatch.setattr(fleet, "_PENDING_KEEP", 8)
        mesh_dir = str(tmp_path / "mesh")
        p0 = fleet.FleetPlane(fleet.FileFleetMesh(mesh_dir, 0), 0, 2)
        m1 = fleet.FileFleetMesh(mesh_dir, 1)
        for b in range(20):
            p0.note_sync("log", b, arrival_unix=1000.0 + b)
            m1.append_sync({"barrier": fleet.barrier_id("log", b),
                            "kind": "log", "step": b, "p": 1,
                            "t": 1000.5 + b, "w": 0.0})
        # host 1 "dies": 30 more coordinator-only barriers
        for b in range(20, 50):
            p0.note_sync("log", b, arrival_unix=1000.0 + b)
        assert len(p0._booked) <= 8
        assert len(p0._booked_order) <= 8
        assert len(p0._pending) <= 8
        assert p0._barriers >= 19          # completed ones all booked

    def test_fleetz_consistent_cut(self, tmp_path):
        """The rollup's goodput aggregate is computed from exactly the
        per-host docs in the same payload."""
        mesh_dir = str(tmp_path / "mesh")
        plane = fleet.FleetPlane(fleet.FileFleetMesh(mesh_dir, 0), 0, 2)
        for p, frac in ((0, 0.5), (1, 0.25)):
            fleet.FileFleetMesh(mesh_dir, p).publish_host(
                {"process": p, "rev": 1, "rev_echo": 1,
                 "goodput": {"productive_s": 10.0 * (p + 1),
                             "wall_s": 20.0 * (p + 1),
                             "productive_fraction": frac}})
        doc = plane.fleetz()
        assert doc["goodput"]["productive_s_total"] == 30.0
        assert doc["goodput"]["wall_s_total"] == 60.0
        assert doc["goodput"]["productive_fraction"] == 0.5
        assert doc["goodput"]["min_host_fraction"] == 0.25
        assert doc["hosts_reporting"] == [0, 1]

    def test_write_rollup_lands_fleet_json(self, tmp_path):
        logs = tmp_path / "logs"
        plane = fleet.FleetPlane(
            fleet.FileFleetMesh(str(tmp_path / "mesh"), 0), 0, 1,
            spans_dir=str(logs))
        path = plane.write_rollup()
        assert path == str(logs / "fleet.json")
        doc = json.loads((logs / "fleet.json").read_text())
        assert doc["coordinator"] == 0

    def test_non_coordinator_never_writes_rollup(self, tmp_path):
        plane = fleet.FleetPlane(
            fleet.FileFleetMesh(str(tmp_path / "mesh"), 1), 1, 2,
            spans_dir=str(tmp_path / "logs"))
        assert plane.write_rollup() is None

    def test_configure_get_reset(self, tmp_path):
        assert fleet.get_plane() is None
        plane = fleet.configure(str(tmp_path / "mesh"), 1, 4,
                                spans_dir=str(tmp_path / "logs"))
        try:
            assert fleet.get_plane() is plane
            assert plane.process == 1 and not plane.is_coordinator
        finally:
            fleet.reset()
        assert fleet.get_plane() is None


def _http_get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, json.loads(r.read())


class TestFleetzEndpoint:
    def test_concurrent_scrapes_never_see_torn_host_docs(self, tmp_path):
        """THE /fleetz consistency pin, at the HTTP layer: host docs are
        republished as fast as possible while scraper threads hammer the
        endpoint — every doc served must carry matching rev/rev_echo
        brackets and an aggregate computed from the served docs."""
        from dtf_tpu.telemetry.live import AdminServer
        mesh_dir = str(tmp_path / "mesh")
        plane = fleet.FleetPlane(fleet.FileFleetMesh(mesh_dir, 0), 0, 2)
        meshes = [fleet.FileFleetMesh(mesh_dir, p) for p in (0, 1)]
        stop = threading.Event()
        write_errors = []

        def writer():
            rev = 0
            while not stop.is_set():
                rev += 1
                for p, m in enumerate(meshes):
                    try:
                        m.publish_host(
                            {"process": p, "rev": rev,
                             "goodput": {"productive_s": float(rev),
                                         "wall_s": 2.0 * rev,
                                         "productive_fraction": 0.5},
                             "rev_echo": rev})
                    except OSError as exc:
                        write_errors.append(exc)

        srv = AdminServer(0, fleet_fn=plane.fleetz).start()
        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        try:
            torn = []

            def scrape():
                for _ in range(25):
                    code, doc = _http_get(srv.port, "/fleetz")
                    assert code == 200
                    hosts = doc.get("hosts", {})
                    for k, h in hosts.items():
                        if h.get("rev") != h.get("rev_echo"):
                            torn.append((k, h.get("rev"),
                                         h.get("rev_echo")))
                    prod = sum(h["goodput"]["productive_s"]
                               for h in hosts.values())
                    if abs(prod
                           - doc["goodput"]["productive_s_total"]) > 1e-6:
                        torn.append(("aggregate", prod,
                                     doc["goodput"]["productive_s_total"]))

            threads = [threading.Thread(target=scrape) for _ in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not torn, torn[:5]
            assert not write_errors
        finally:
            stop.set()
            wt.join(timeout=5)
            srv.close()

    def test_unarmed_returns_note(self):
        from dtf_tpu.telemetry.live import AdminServer
        srv = AdminServer(0).start()
        try:
            code, doc = _http_get(srv.port, "/fleetz")
            assert code == 200 and doc["fleet"] is None
            code, idx = _http_get(srv.port, "/")
            assert "/fleetz" in idx["endpoints"]
        finally:
            srv.close()


class TestReportIntegration:
    def _write_spans(self, logdir, events):
        os.makedirs(logdir, exist_ok=True)
        by_pid = {}
        for e in events:
            by_pid.setdefault(e["pid"], []).append(
                {"name": "fleet/sync", "ph": "X", "pid": e["pid"],
                 "tid": 1, "ts": e["arrive_s"] * 1e6,
                 "dur": e["wait_s"] * 1e6,
                 "args": {"barrier": e["barrier"], "kind": e["kind"],
                          "step": e["step"], "host": e["pid"]}})
        for pid, recs in by_pid.items():
            with open(os.path.join(logdir, f"spans.p{pid}.jsonl"),
                      "w") as f:
                for r in recs:
                    f.write(json.dumps(r) + "\n")

    def test_build_report_fleet_section_and_gates(self, tmp_path):
        from dtf_tpu.telemetry.report import (build_report, check_gates,
                                              render)
        logdir = str(tmp_path)
        self._write_spans(logdir, _mk_events(offsets=(0.0, 1.0),
                                             lateness=(0.0, 0.2)))
        with open(os.path.join(logdir, "fleet.json"), "w") as f:
            json.dump({"nproc": 2, "hosts": {"0": {}, "1": {}},
                       "written_unix": 1.0,
                       "goodput": {"productive_fraction": 0.4}}, f)
        rep = build_report(logdir)
        att = rep["fleet"]["attribution"]
        assert att["per_host"]["1"]["blame_frac"] == 1.0
        assert abs(float(rep["fleet"]["offsets_s"]["1"]) - 1.0) < 1e-3
        ok, lines = check_gates(rep, max_skew_ms=400.0,
                                min_fleet_goodput=0.3,
                                max_blame_frac=1.0)
        assert ok, lines
        # falsifiability: absurd thresholds fail the same report
        ok, lines = check_gates(rep, max_skew_ms=0.001)
        assert not ok
        ok, lines = check_gates(rep, max_blame_frac=0.01)
        assert not ok
        ok, lines = check_gates(rep, min_fleet_goodput=0.9)
        assert not ok
        text = render(rep)
        assert "Fleet (telemetry/fleet.py)" in text
        assert "drift" in text

    def test_rollup_live_attribution_feeds_gates_without_spans(
            self, tmp_path):
        """Node-local logdirs / tcp:// meshes leave no merged span
        stream on the judged logdir — the coordinator's LIVE
        attribution in fleet.json must stand in so the skew/blame gates
        judge real measurements instead of failing on absence."""
        from dtf_tpu.telemetry.report import (build_report, check_gates,
                                              render)
        with open(os.path.join(str(tmp_path), "fleet.json"), "w") as f:
            json.dump({"nproc": 2, "written_unix": 1.0,
                       "hosts": {"0": {}, "1": {}},
                       "goodput": {"productive_fraction": 0.3},
                       "attribution": {
                           "barriers": 10,
                           "skew_ms_p50": 120.0, "skew_ms_max": 300.0,
                           "blame": {"1": 9, "0": 1},
                           "lateness_s": {"1": 0.9, "0": 0.05},
                           "offsets_s": {}}}, f)
        rep = build_report(str(tmp_path))
        att = rep["fleet"]["attribution"]
        assert rep["fleet"]["attribution_source"] == "rollup_live"
        assert att["per_host"]["1"]["blame_frac"] == 0.9
        ok, lines = check_gates(rep, max_skew_ms=500.0,
                                min_fleet_goodput=0.1,
                                max_blame_frac=0.95)
        assert ok, lines
        ok, _ = check_gates(rep, max_skew_ms=1.0)
        assert not ok
        text = render(rep)
        assert "source: rollup_live" in text and "n/a" in text

    def test_span_attribution_wins_over_rollup_live(self, tmp_path):
        """When both sources exist the span-based (offset-corrected)
        attribution is the one judged."""
        from dtf_tpu.telemetry.report import build_report
        self._write_spans(str(tmp_path), _mk_events())
        with open(os.path.join(str(tmp_path), "fleet.json"), "w") as f:
            json.dump({"nproc": 2, "hosts": {},
                       "attribution": {"barriers": 1,
                                       "blame": {"0": 1},
                                       "lateness_s": {},
                                       "skew_ms_p50": 1.0}}, f)
        rep = build_report(str(tmp_path))
        assert rep["fleet"]["attribution_source"] == "spans"
        assert rep["fleet"]["attribution"]["barriers"] > 1

    def test_fleet_gates_absence_is_failure(self, tmp_path):
        """A gated-but-unmeasured fleet quantity FAILS — same absence
        rule as every other gate."""
        from dtf_tpu.telemetry.report import build_report, check_gates
        rep = build_report(str(tmp_path))      # empty logdir
        ok, lines = check_gates(rep, max_skew_ms=1000.0)
        assert not ok and "not measured" in lines[0]
        ok, lines = check_gates(rep, min_fleet_goodput=0.1)
        assert not ok
        ok, lines = check_gates(rep, max_blame_frac=0.9)
        assert not ok

    def test_cli_fleet_flag_requires_fleet_data(self, tmp_path):
        from dtf_tpu.telemetry.report import main
        assert main([str(tmp_path), "--fleet"]) == 1
        self._write_spans(str(tmp_path), _mk_events())
        assert main([str(tmp_path), "--fleet"]) == 0

    def test_export_trace_rebases_offsets(self, tmp_path):
        """--export-trace on a fleet logdir subtracts each host's
        estimated offset so the merged trace is one timeline, and names
        + sorts one track-group per host."""
        from dtf_tpu.telemetry.report import main
        ev = _mk_events(offsets=(0.0, 3.5), lateness=(0.0, 0.2))
        self._write_spans(str(tmp_path), ev)
        out = str(tmp_path / "trace.json")
        assert main([str(tmp_path), "--export-trace", out]) == 0
        doc = json.load(open(out))
        by_pid = {}
        for e in doc["traceEvents"]:
            if e.get("ph") == "X":
                by_pid.setdefault(e["pid"], []).append(e)
        # after rebase, the two hosts' first-barrier releases coincide
        rel0 = min(e["ts"] + e["dur"] for e in by_pid[0])
        rel1 = min(e["ts"] + e["dur"] for e in by_pid[1])
        assert abs(rel0 - rel1) < 2e3        # < 2 ms in µs
        meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
        names = {e["pid"]: e["args"].get("name") for e in meta
                 if e["name"] == "process_name"}
        assert "clock" in names[1] and "clock" not in names[0]
        assert any(e["name"] == "process_sort_index" for e in meta)


class TestReqtraceFleetStream:
    def _reqtrace_rec(self, pid, rid, trace_id, phase, t):
        return {"name": f"reqtrace/{phase}", "ph": "i", "pid": pid,
                "tid": 7, "ts": t * 1e6, "s": "p",
                "args": {"trace_id": trace_id, "rid": rid, "t": t}}

    def test_same_rid_on_two_hosts_renders_per_host(self, tmp_path):
        """rids are per-engine: the merged fleet stream carries rid 0 on
        both hosts as two DIFFERENT requests — the timeline renders both
        segments contiguously with their host labels, and --pid narrows
        to one."""
        from dtf_tpu.telemetry import reqtrace
        chain = ("submit", "admitted", "prefill", "first_token",
                 "completed")
        for pid, tid0 in ((0, "aa" * 8), (1, "bb" * 8)):
            # host 1's stream is split across a rotated generation and
            # the active tail — readers must walk both as one stream
            paths = ([f"spans.p{pid}.jsonl"] if pid == 0 else
                     [f"spans.p{pid}.000.jsonl", f"spans.p{pid}.jsonl"])
            recs = [self._reqtrace_rec(pid, 0, tid0, ph, 10.0 + i)
                    for i, ph in enumerate(chain)]
            half = len(recs) // 2
            chunks = ([recs] if len(paths) == 1
                      else [recs[:half], recs[half:]])
            for path, chunk in zip(paths, chunks):
                with open(tmp_path / path, "w") as f:
                    for r in chunk:
                        f.write(json.dumps(r) + "\n")
        events = reqtrace.request_timeline(str(tmp_path), 0)
        assert {e["pid"] for e in events} == {0, 1}
        # each host's segment is contiguous and in chain order
        for pid in (0, 1):
            seg = [e["phase"] for e in events if e["pid"] == pid]
            assert seg == list(chain)
        lines = reqtrace.render_timeline(events)
        assert any("hosts: [0, 1]" in ln for ln in lines)
        assert any(ln.strip().startswith("p1") for ln in lines)
        only1 = reqtrace.request_timeline(str(tmp_path), 0, pid=1)
        assert {e["pid"] for e in only1} == {1}
        # completeness sees two complete traces (distinct trace ids)
        traces = reqtrace.group_traces(
            reqtrace.load_request_events(str(tmp_path)))
        comp = reqtrace.completeness(traces)
        assert comp["completed"] == 2 and comp["complete"] == 2


class TestNames:
    def test_fleet_family_declared(self):
        from dtf_tpu.telemetry.names import is_declared
        for name in ("fleet/sync", "fleet/barriers_total",
                     "fleet/skew_ms", "fleet/blame_p7",
                     "fleet/lateness_s_p0", "fleet/hosts"):
            assert is_declared(name), name
        assert not is_declared("fleet/not_a_thing")

    def test_strict_registry_accepts_fleet_names(self):
        from dtf_tpu.telemetry.registry import get_registry
        reg = get_registry()
        reg.counter("fleet/blame_p3")
        with pytest.raises(ValueError):
            reg.counter("fleet/definitely_not_declared")


class TestScenarioGateWiring:
    def test_gate_thresholds_carry_fleet_gates(self):
        from dtf_tpu.scenarios.spec import Gate
        g = Gate(max_final_cost=1.0, min_goodput=0.1, max_skew_ms=500.0,
                 min_fleet_goodput=0.05, max_blame_frac=0.9)
        th = g.thresholds()
        assert th["max_skew_ms"] == 500.0
        assert th["min_fleet_goodput"] == 0.05
        assert th["max_blame_frac"] == 0.9
        th0 = Gate(max_final_cost=1.0, min_goodput=0.1).thresholds()
        assert "max_skew_ms" not in th0

    def test_elastic_cell_arms_fleet_gates(self):
        from dtf_tpu.scenarios.spec import default_matrix
        cell = {c.name: c for c in default_matrix()}[
            "mnist_host_down_elastic"]
        assert cell.gate.max_skew_ms > 0
        assert cell.gate.min_fleet_goodput > 0


@pytest.mark.chaos
class TestFleetTwoProcess:
    """The 2-process A/B (acceptance): a REAL fleet run with an injected
    slow_host straggler.  Slow-listed in conftest; one shared run feeds
    every assertion."""

    DELAY_MS = 40.0
    STEPS = 40

    @pytest.fixture(scope="class")
    def fleet_run(self, tmp_path_factory):
        shared = tmp_path_factory.mktemp("fleet_mp")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        inherited = [
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and not os.path.exists(
                os.path.join(p, "sitecustomize.py"))]
        env["PYTHONPATH"] = os.pathsep.join([REPO_ROOT, *inherited])
        driver = os.path.join(REPO_ROOT, "tests", "_mp_fleet.py")
        procs = [subprocess.Popen(
            [sys.executable, driver, str(task), "2", str(shared),
             str(self.STEPS), "2", f"slow_host@0:1:{self.DELAY_MS:.0f}ms"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for task in range(2)]
        outs = []
        try:
            for task, p in enumerate(procs):
                out, _ = p.communicate(timeout=420)
                outs.append(out)
                assert p.returncode == 0, \
                    f"host {task} failed:\n{out[-3000:]}"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        assert "MP_FLEET_DONE" in outs[0]
        return str(shared)

    def test_blame_lands_on_injected_host(self, fleet_run):
        """>= 80% of last-arrival blame on exactly the slow_host target,
        and the measured drift matches the injected delay within
        tolerance (box-load jitter allowed for)."""
        from dtf_tpu.telemetry.report import build_report
        rep = build_report(os.path.join(fleet_run, "logs"))
        att = rep["fleet"]["attribution"]
        per = att["per_host"]
        assert per["1"]["blame_frac"] >= 0.8, per
        assert per["1"]["blame_frac"] > per["0"]["blame_frac"]
        drift = per["1"]["drift_ms_per_step"]
        assert 0.4 * self.DELAY_MS <= drift <= 2.2 * self.DELAY_MS, \
            f"drift {drift} vs injected {self.DELAY_MS} ms/step"
        assert att["barriers"] >= 5
        assert att["skew_ms_p50"] > 0

    def test_merged_trace_completeness(self, fleet_run):
        """Both hosts' span streams land in the shared logdir; every
        barrier the fleet completed carries BOTH hosts' fleet/sync
        marks, and both hosts' train steps export into one trace."""
        from dtf_tpu.telemetry import reqtrace
        from dtf_tpu.telemetry.spans import find_span_files
        logs = os.path.join(fleet_run, "logs")
        files = [os.path.basename(p) for p in find_span_files(logs)]
        assert "spans.p0.jsonl" in files and "spans.p1.jsonl" in files
        records = reqtrace.read_all_records(logs)
        ev = fleet.sync_events(records)
        by_barrier = {}
        for e in ev:
            by_barrier.setdefault(e["barrier"], set()).add(e["pid"])
        complete = [b for b, pids in by_barrier.items()
                    if pids == {0, 1}]
        assert len(complete) >= 5, by_barrier
        steps_by_pid = {}
        for r in records:
            if r.get("name") == "train/step" and r.get("ph") == "X":
                steps_by_pid.setdefault(r.get("pid"), 0)
                steps_by_pid[r.get("pid")] += 1
        assert steps_by_pid.get(0, 0) >= self.STEPS
        assert steps_by_pid.get(1, 0) >= self.STEPS

    def test_gates_pass_sane_fail_absurd(self, fleet_run):
        """report --fleet greenlights sane thresholds and FAILS absurd
        ones on the same logdir (falsifiability, same pattern as the
        scenario runner)."""
        from dtf_tpu.telemetry.report import main
        logs = os.path.join(fleet_run, "logs")
        assert main([logs, "--fleet", "--max_skew_ms", "10000",
                     "--min_fleet_goodput", "0.0001"]) == 0
        assert main([logs, "--max_skew_ms", "0.001"]) == 1
        assert main([logs, "--max_blame_frac", "0.01"]) == 1

    def test_rollup_consistent(self, fleet_run):
        doc = json.loads(open(
            os.path.join(fleet_run, "logs", "fleet.json")).read())
        assert doc["nproc"] == 2
        assert doc["hosts_reporting"] == ["0", "1"] or \
            doc["hosts_reporting"] == [0, 1]
        g = doc["goodput"]
        assert g["wall_s_total"] > 0
        prod = sum(h["goodput"]["productive_s"]
                   for h in doc["hosts"].values())
        assert abs(prod - g["productive_s_total"]) < 1e-6
        for h in doc["hosts"].values():
            assert h["rev"] == h["rev_echo"]
