"""T5 relative position bucketing vs closed-form values, the bias module,
and RMSNorm numerics."""

import jax
import jax.numpy as jnp
import numpy as np

from dtf_tpu.nn.layers import RMSNorm
from dtf_tpu.nn.relpos import RelativePositionBias, relative_position_bucket


class TestBucketing:
    """Hand-computed values of the canonical T5 scheme (num_buckets=32,
    max_distance=128).  rel = key_pos - query_pos."""

    def test_bidirectional_closed_form(self):
        # n = 16 per direction, max_exact = 8:
        #   rel<=0 -> buckets [0,16), rel>0 -> [16,32)
        #   |rel| < 8 exact; 8..127 log-spaced 8..15; >=128 clamps to 15
        cases = {
            0: 0, -1: 1, -7: 7,
            -8: 8,                        # first log bucket == max_exact
            -127: 15, -128: 15, -10000: 15,
            1: 17, 7: 23, 8: 24, 127: 31, 10000: 31,
        }
        rel = jnp.asarray(list(cases.keys()))
        got = relative_position_bucket(rel, bidirectional=True,
                                       num_buckets=32, max_distance=128)
        np.testing.assert_array_equal(got, list(cases.values()))

    def test_unidirectional_closed_form(self):
        # n = 32, max_exact = 16; future keys (rel > 0) all -> bucket 0
        cases = {
            5: 0, 1: 0, 0: 0,
            -1: 1, -15: 15,
            -16: 16,                      # first log bucket
            -127: 31, -1000: 31,
        }
        rel = jnp.asarray(list(cases.keys()))
        got = relative_position_bucket(rel, bidirectional=False,
                                       num_buckets=32, max_distance=128)
        np.testing.assert_array_equal(got, list(cases.values()))

    def test_log_buckets_monotone_nondecreasing(self):
        d = -jnp.arange(0, 4096)
        b = relative_position_bucket(d, bidirectional=False)
        assert bool(jnp.all(jnp.diff(b) >= 0))
        assert int(b.max()) == 31


class TestBiasModule:
    def test_shape_and_sharing(self):
        m = RelativePositionBias(num_heads=4)
        p = m.init(jax.random.key(0))
        q = jnp.arange(8)
        bias = m.apply(p, q, q)
        assert bias.shape == (1, 4, 8, 8)
        # same relative offset -> same bias (diagonal bands constant)
        band0 = np.asarray(bias[0, 0]).diagonal()
        assert np.allclose(band0, band0[0])

    def test_decode_row_matches_full_matrix(self):
        """The (1, H, 1, T) bias generate() computes per position must be
        the matching row of the full (1, H, T, T) teacher-forced bias."""
        m = RelativePositionBias(num_heads=2, bidirectional=False)
        p = m.init(jax.random.key(1))
        pos = jnp.arange(12)
        full = m.apply(p, pos, pos)
        for q in (0, 5, 11):
            row = m.apply(p, jnp.asarray([q]), pos)
            np.testing.assert_array_equal(row[0, :, 0], full[0, :, q])


class TestRMSNorm:
    def test_matches_formula(self):
        m = RMSNorm(dim=16)
        p = m.init(jax.random.key(0))
        p = {"scale": p["scale"] * 2.0}
        x = jax.random.normal(jax.random.key(1), (3, 16)) * 5 + 1
        got = m.apply(p, x)
        want = x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True)
                           + 1e-6) * 2.0
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_no_mean_subtraction(self):
        # constant input keeps its sign/scale (unlike LayerNorm -> 0)
        m = RMSNorm(dim=8)
        p = m.init(jax.random.key(0))
        x = jnp.full((1, 8), 3.0)
        np.testing.assert_allclose(m.apply(p, x), jnp.ones((1, 8)),
                                   rtol=1e-4)
