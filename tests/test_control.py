"""Self-tuning control plane (dtf_tpu/control): knob registry rails,
controller safety guards, adversarial load shapes, /controlz.

The headline pin is falsifiability: an injected ALWAYS-WORSENING policy
on a real engine run must be caught by the safety rails and snapped
back to the pinned defaults within its improvement window, booked under
``control/rollback_total`` — "self-tuning" that cannot be shown to
reject a bad policy is just a second way to misconfigure the server.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

import dtf_tpu.telemetry as tel
from dtf_tpu.control import (KnobController, KnobRegistry, arm_controller,
                             wire_serve_knobs)

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    tel.reset()
    yield
    tel.reset()


@pytest.fixture(scope="module")
def tiny_model():
    from dtf_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig.tiny())
    return model, model.init(jax.random.key(0))


def _reg_one(name="spec_k", **kw):
    reg = KnobRegistry()
    kw.setdefault("lo", 0)
    kw.setdefault("hi", 8)
    kw.setdefault("quantum", 1)
    kw.setdefault("default", 2)
    kw.setdefault("apply", lambda v: None)
    reg.register(name, **kw)
    return reg


# ---------------------------------------------------------------------------
# knob registry: the ONE audited mutation path
# ---------------------------------------------------------------------------


class TestKnobRegistry:
    def test_bounds_clamp(self):
        reg = _reg_one(max_step=100)
        assert reg.set("spec_k", 99, iteration=0, reason="t") == (2.0, 8.0)
        assert reg.get("spec_k") == 8.0
        assert reg.set("spec_k", -99, iteration=1, reason="t") == (8.0, 0.0)
        assert reg.get("spec_k") == 0.0

    def test_quantum_snap_anchored_at_lo(self):
        reg = _reg_one("aging_s", lo=0.25, hi=8.0, quantum=0.25,
                       default=1.0, max_step=100)
        reg.set("aging_s", 1.37, iteration=0, reason="t")
        assert reg.get("aging_s") == pytest.approx(1.25)
        reg.set("aging_s", 1.38, iteration=1, reason="t")
        assert reg.get("aging_s") == pytest.approx(1.5)

    def test_max_step_clamps_and_books(self):
        reg = _reg_one(max_step=1)
        assert reg.set("spec_k", 8, iteration=0, reason="t") == (2.0, 3.0)
        assert tel.counter("control/clamped_total").value == 1

    def test_cooldown_refuses_and_books(self):
        reg = _reg_one(cooldown_iters=16)
        assert reg.set("spec_k", 3, iteration=0, reason="t") is not None
        # iteration 8 is inside the 16-iteration cooldown: refused
        assert reg.set("spec_k", 4, iteration=8, reason="t") is None
        assert reg.get("spec_k") == 3.0
        assert tel.counter("control/cooldown_skips_total").value == 1
        assert reg.set("spec_k", 4, iteration=16, reason="t") is not None

    def test_noop_set_books_nothing(self):
        reg = _reg_one()
        assert reg.set("spec_k", 2, iteration=0, reason="t") is None
        assert tel.counter("control/sets_total").value == 0
        assert not reg.snapshot()["audit"]

    def test_bad_declarations_raise(self):
        reg = _reg_one()
        with pytest.raises(ValueError, match="already registered"):
            reg.register("spec_k", lo=0, hi=1, quantum=1, default=0,
                         apply=lambda v: None)
        with pytest.raises(ValueError, match="outside bounds"):
            reg.register("x", lo=0, hi=1, quantum=1, default=5,
                         apply=lambda v: None)
        with pytest.raises(ValueError, match="quantum"):
            reg.register("y", lo=0, hi=1, quantum=0, default=0,
                         apply=lambda v: None)
        with pytest.raises(ValueError, match="unknown knob"):
            reg.set("nope", 1, iteration=0, reason="t")

    def test_apply_callback_pushes_value(self):
        seen = []
        reg = _reg_one(apply=seen.append)
        reg.set("spec_k", 3, iteration=0, reason="t")
        assert seen == [3.0]

    def test_register_is_eagerly_visible_in_telemetry(self):
        _reg_one()
        assert tel.gauge("control/knob_spec_k").value == 2.0

    def test_reset_to_defaults_idempotent(self):
        reg = _reg_one(max_step=100)
        reg.register("aging_s", lo=0.25, hi=8.0, quantum=0.25, default=1.0,
                     apply=lambda v: None)
        reg.set("spec_k", 8, iteration=0, reason="t")
        assert not reg.at_defaults()
        moved = reg.reset_to_defaults(iteration=5, reason="fast_burn")
        assert moved == ["spec_k"]      # aging_s never moved: books nothing
        assert reg.at_defaults()
        sets_after = tel.counter("control/sets_total").value
        # second reset is a no-op: no audit entries, no counter motion
        assert reg.reset_to_defaults(iteration=6, reason="fast_burn") == []
        assert tel.counter("control/sets_total").value == sets_after

    def test_rollback_bypasses_cooldown_and_max_step(self):
        reg = _reg_one(max_step=1, cooldown_iters=100)
        reg.set("spec_k", 3, iteration=0, reason="t")
        # iteration 1 is deep inside the cooldown and 1 < |3 - 2| + 1,
        # yet the snap-back lands in ONE move: safety actions are never
        # rate-limited by the rails they are undoing
        assert reg.reset_to_defaults(iteration=1, reason="r") == ["spec_k"]
        assert reg.get("spec_k") == 2.0

    def test_snapshot_consistent_under_concurrent_sets(self):
        """Torn-pair pin: a snapshot taken while writer threads mutate
        must never show a knob value without its matching audit entry —
        the last audit row for a knob always lands on the value seen."""
        reg = _reg_one(max_step=100)
        stop = threading.Event()
        it = [0]

        def writer():
            while not stop.is_set():
                it[0] += 1
                reg.set("spec_k", it[0] % 9, iteration=it[0], reason="w")

        threads = [threading.Thread(target=writer) for _ in range(2)]
        for t in threads:
            t.start()
        try:
            for _ in range(300):
                snap = reg.snapshot()
                tail = [e for e in snap["audit"] if e["knob"] == "spec_k"]
                if tail:
                    assert tail[-1]["new"] == snap["knobs"]["spec_k"]["value"]
        finally:
            stop.set()
            for t in threads:
                t.join()


# ---------------------------------------------------------------------------
# controller: rails driven deterministically through a scripted SLO
# ---------------------------------------------------------------------------


class _ScriptedSLO:
    """Stands in for BurnRateMonitor.state(): the test scripts bad/event
    counts and the edge-triggered fast-alert counter directly."""

    def __init__(self):
        self.bad = 0
        self.events = 0
        self.alerts_fast = 0
        self.firing_fast = False

    def state(self):
        return {"objectives": {"ttft": {
            "bad_total": self.bad, "events_total": self.events,
            "alerts_fast": self.alerts_fast,
            "firing_fast": self.firing_fast}}}


def _hostile(signals, knobs):
    return [("spec_k", +1, "sabotage")]


class TestControllerRails:
    def test_requires_slo(self):
        with pytest.raises(ValueError, match="BurnRateMonitor"):
            KnobController(_reg_one(), slo=None)

    def test_rollback_counter_registers_eagerly(self):
        """Armed-with-zero-rollbacks must be distinguishable from
        never-armed: the counter exists (at 0) from construction."""
        assert "control/rollback_total" not in \
            tel.get_registry().snapshot()
        KnobController(_reg_one(), slo=_ScriptedSLO())
        snap = tel.get_registry().snapshot()
        assert snap["control/rollback_total"]["value"] == 0

    def test_period_gates_evaluation(self):
        slo = _ScriptedSLO()
        ctl = KnobController(_reg_one(), slo=slo, policy=_hostile, period=8)
        for i in range(17):
            ctl.decide(0.0, i)
        assert ctl.decisions == 3        # iterations 0, 8, 16

    def test_no_improvement_snaps_back_within_window(self):
        reg = _reg_one()
        slo = _ScriptedSLO()
        slo.events, slo.bad = 20, 0      # healthy before the decision
        ctl = KnobController(reg, slo=slo, policy=_hostile, period=1,
                             improve_window=4, improve_margin=0.05,
                             min_window_events=2)
        ctl.decide(0.0, 0)               # hostile set lands, window opens
        assert reg.get("spec_k") == 3.0
        slo.events, slo.bad = 30, 8      # post-decision window: 80% bad
        ctl.decide(0.0, 4)
        assert reg.at_defaults()
        assert ctl.rollback_reasons == {"no_improvement": 1}
        assert tel.counter("control/rollback_total").value == 1

    def test_decision_that_improves_survives_its_window(self):
        reg = _reg_one()
        slo = _ScriptedSLO()
        slo.events, slo.bad = 20, 10     # 50% bad before
        ctl = KnobController(reg, slo=slo, policy=_hostile, period=1,
                             improve_window=4, improve_margin=0.05,
                             min_window_events=2)
        ctl.decide(0.0, 0)
        slo.events, slo.bad = 40, 11     # 5% bad after: improved
        ctl.decide(0.0, 4)
        assert not reg.at_defaults()     # kept (and hostile moved again)
        assert ctl.rollbacks == 0

    def test_fast_burn_is_edge_triggered(self):
        """A NEW alert after a knob moved snaps back; an alert count
        that was already advancing while at defaults does not."""
        reg = _reg_one()
        slo = _ScriptedSLO()
        ctl = KnobController(reg, slo=slo, policy=_hostile, period=1,
                             improve_window=1000)
        slo.alerts_fast = 3              # background burn, knobs pinned
        ctl.decide(0.0, 0)               # seeds the edge detector + sets
        assert not reg.at_defaults() and ctl.rollbacks == 0
        ctl.decide(0.0, 1)               # count unchanged: level, not edge
        assert ctl.rollbacks == 0
        slo.alerts_fast = 4              # NEW alert with knobs off-pin
        ctl.decide(0.0, 2)
        assert reg.at_defaults()
        assert ctl.rollback_reasons == {"fast_burn": 1}

    def test_hold_off_after_rollback(self):
        reg = _reg_one()
        slo = _ScriptedSLO()
        ctl = KnobController(reg, slo=slo, policy=_hostile, period=1,
                             improve_window=1000, hold_iters=50)
        ctl.decide(0.0, 0)
        slo.alerts_fast = 1
        ctl.decide(0.0, 1)               # fast-burn rollback, hold starts
        assert ctl.rollbacks == 1
        ctl.decide(0.0, 10)              # inside the hold: no proposals
        assert reg.at_defaults()
        ctl.decide(0.0, 51)              # hold expired: policy runs again
        assert not reg.at_defaults()

    def test_controlz_state_payload(self):
        ctl = KnobController(_reg_one(), slo=_ScriptedSLO(),
                             policy=_hostile, period=1)
        ctl.decide(0.0, 0)
        doc = json.loads(json.dumps(ctl.state()))   # must be JSON-clean
        assert doc["knobs"]["spec_k"]["value"] == 3.0
        assert doc["controller"]["decisions"] == 1
        assert doc["audit"][0]["reason"] == "sabotage"


# ---------------------------------------------------------------------------
# wiring + engine-run falsifiability
# ---------------------------------------------------------------------------


def _mk_engine(model, params, **kw):
    from dtf_tpu.serve import (BrownoutController, ServingEngine,
                               VirtualClock)
    from dtf_tpu.telemetry.slo import BurnRateMonitor
    kw.setdefault("clock", VirtualClock())
    kw.setdefault("num_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("blocks_per_slot", 8)
    kw.setdefault("brownout", BrownoutController(100.0))
    kw.setdefault("slo", BurnRateMonitor.for_serving(100.0))
    return ServingEngine(model, params, mode="continuous", **kw)


def _mk_trace(n, *, qps=60.0, vocab=12, seed=0):
    rng = np.random.default_rng(seed)
    trace, t = [], 0.0
    for rid in range(n):
        t += float(rng.exponential(1.0)) / qps
        trace.append((t, {
            "rid": rid,
            "prompt": rng.integers(0, vocab, (8,)).astype(np.int32),
            "max_new_tokens": 8, "temperature": 0.0,
            "deadline_ms": 2500.0}))
    return trace


class TestWireAndFalsifiability:
    def test_wire_pins_defaults_and_disjoint_brownout_ranges(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params, spec_k=2)
        snap = wire_serve_knobs(KnobRegistry(), eng).snapshot()["knobs"]
        assert snap["spec_k"]["default"] == 2.0
        assert snap["prefill_token_budget"]["default"] == \
            eng.scheduler.prefill_token_budget
        # no audited walk can violate 0 < exit < enter
        assert snap["brownout_exit_ratio"]["hi"] \
            < snap["brownout_enter_ratio"]["lo"]

    def test_armed_engine_runs_and_reports(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params)
        ctl = arm_controller(eng)
        assert eng.controller is ctl
        eng.run(_mk_trace(24))
        out = eng.summary(slo_ttft_ms=100.0)
        assert out["control"]["decisions"] > 0
        assert set(out["control"]["knobs"]) == set(ctl.registry.names())

    def test_hostile_policy_snaps_back_on_real_run(self, tiny_model):
        """The falsifiability pin: a policy that can only ever hurt —
        every decision disables the brownout ladder and inflates the
        degraded-answer budget — is rolled back to the pinned defaults
        by the rails mid-run, booked under control/rollback_total."""
        model, params = tiny_model
        eng = _mk_engine(model, params)

        def vandal(signals, knobs):
            return [("brownout_enter_ratio", +10.0, "sabotage"),
                    ("degrade_max_new", +100.0, "sabotage"),
                    ("prefill_token_budget", -10000.0, "sabotage")]

        ctl = arm_controller(eng, policy=vandal, period=4,
                             improve_window=16, improve_margin=0.05,
                             min_window_events=2)
        eng.run(_mk_trace(48, qps=120.0))
        assert ctl.rollbacks >= 1
        assert sum(ctl.rollback_reasons.values()) == ctl.rollbacks
        assert tel.counter("control/rollback_total").value \
            == ctl.rollbacks
        # the snap-back is in the span record too (the audited path)
        assert ctl.registry.at_defaults() or ctl.rollbacks >= 1


# ---------------------------------------------------------------------------
# adversarial load shapes (bench/serve_load qps_profile)
# ---------------------------------------------------------------------------


class TestQpsProfiles:
    def test_same_contents_different_arrivals(self):
        from dtf_tpu.bench.serve_load import QPS_PROFILES, poisson_trace
        traces = {p: poisson_trace(seed=7, n_requests=24, qps=20.0,
                                   prompt_lens=[4, 8], output_lens=[4],
                                   vocab_size=32, qps_profile=p)
                  for p in QPS_PROFILES}
        base = traces["constant"]
        for p, tr in traces.items():
            assert len(tr) == len(base)
            times = [t for t, _ in tr]
            assert times == sorted(times)        # arrivals stay monotone
            for (_, a), (_, b) in zip(tr, base):
                # identical request CONTENTS: the rng draw order is
                # preserved, only the arrival clock is warped
                assert a["rid"] == b["rid"]
                assert np.array_equal(a["prompt"], b["prompt"])
                assert a["max_new_tokens"] == b["max_new_tokens"]
            if p != "constant":
                assert times != [t for t, _ in base]

    def test_profiles_deterministic(self):
        from dtf_tpu.bench.serve_load import poisson_trace
        a = poisson_trace(seed=3, n_requests=10, qps=10.0,
                          prompt_lens=[4], output_lens=[4],
                          vocab_size=16, qps_profile="sine")
        b = poisson_trace(seed=3, n_requests=10, qps=10.0,
                          prompt_lens=[4], output_lens=[4],
                          vocab_size=16, qps_profile="sine")
        assert [t for t, _ in a] == [t for t, _ in b]

    def test_invalid_profile_raises(self):
        from dtf_tpu.bench.serve_load import poisson_trace
        with pytest.raises(ValueError, match="qps_profile"):
            poisson_trace(seed=0, n_requests=4, qps=10.0,
                          prompt_lens=[4], output_lens=[4],
                          vocab_size=16, qps_profile="sawtooth")


# ---------------------------------------------------------------------------
# gates + /controlz endpoint
# ---------------------------------------------------------------------------


class TestKnobGates:
    ON = {"goodput_qps": 12.0, "ttft_ms_p99": 80.0, "tpot_ms_p99": 10.0,
          "control": {"decisions": 5, "sets": 3, "rollbacks": 1,
                      "rollback_reasons": {"no_improvement": 1},
                      "knobs": {"spec_k": 3.0}}}
    OFF = {"goodput_qps": 10.0, "ttft_ms_p99": 90.0, "tpot_ms_p99": 11.0}

    def test_all_pass(self):
        from dtf_tpu.bench.serve_load import knob_gates
        ok, lines = knob_gates(self.ON, self.OFF, 2)
        assert ok, lines

    def test_each_gate_fails_on_its_own_axis(self):
        from dtf_tpu.bench.serve_load import knob_gates
        tie = dict(self.ON, goodput_qps=10.0)     # tie is NOT a win
        assert not knob_gates(tie, self.OFF, None)[0]
        slow = dict(self.ON, ttft_ms_p99=95.0)
        assert not knob_gates(slow, self.OFF, None)[0]
        idle = dict(self.ON, control=dict(self.ON["control"], sets=0))
        assert not knob_gates(idle, self.OFF, None)[0]
        unexplained = dict(self.ON, control=dict(
            self.ON["control"], rollback_reasons={}))
        assert not knob_gates(unexplained, self.OFF, None)[0]
        assert not knob_gates(self.ON, self.OFF, 0)[0]  # bound exceeded

    def test_check_gates_rollback_bound_fails_on_absence(self):
        """--max_control_rollbacks armed against a run that never armed
        the controller must FAIL: absence is not zero."""
        from dtf_tpu.telemetry.report import check_gates
        bare = {"telemetry": {"metrics": {}}}
        ok, lines = check_gates(bare, max_control_rollbacks=2)
        assert not ok
        armed = {"telemetry": {"metrics": {
            "control/rollback_total": {"value": 0}}}}
        ok, _ = check_gates(armed, max_control_rollbacks=2)
        assert ok
        hot = {"telemetry": {"metrics": {
            "control/rollback_total": {"value": 3}}}}
        assert not check_gates(hot, max_control_rollbacks=2)[0]


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
        return r.status, json.loads(r.read())


class TestControlzEndpoint:
    def test_unarmed_returns_note(self):
        from dtf_tpu.telemetry.live import AdminServer
        srv = AdminServer(0).start()
        try:
            code, doc = _get(srv.port, "/controlz")
            assert code == 200 and doc["control"] is None
            assert "no knob controller" in doc["note"]
        finally:
            srv.close()

    def test_armed_serves_controller_state(self):
        from dtf_tpu.telemetry.live import AdminServer
        ctl = KnobController(_reg_one(), slo=_ScriptedSLO(),
                             policy=_hostile, period=1)
        ctl.decide(0.0, 0)
        srv = AdminServer(0, control_fn=ctl.state).start()
        try:
            code, doc = _get(srv.port, "/controlz")
            assert code == 200
            assert doc["knobs"]["spec_k"]["value"] == 3.0
            assert doc["controller"]["decisions"] == 1
            code, idx = _get(srv.port, "/")
            assert "/controlz" in idx["endpoints"]
        finally:
            srv.close()
