"""Beam search decoding (models/gpt.py::beam_search): beam-1 == greedy,
score ordering and correctness against exhaustive enumeration on a tiny
vocab, EOS freezing, and cache reordering across beam switches."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.models.gpt import GPT, GPTConfig


@pytest.fixture(scope="module")
def model():
    return GPT(GPTConfig.tiny(vocab_size=16, dim=16, num_heads=2,
                              mlp_dim=32, max_len=32))


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def seq_logprob(model, params, seq, p_len):
    """Sum of next-token log-probs for seq[p_len:] under the model."""
    logits = model.apply(params, seq[None])[0]
    logp = jax.nn.log_softmax(logits, axis=-1)
    tot = 0.0
    for t in range(p_len, len(seq)):
        tot += float(logp[t - 1, int(seq[t])])
    return tot


class TestBeamSearch:
    def test_beam1_equals_greedy(self, model, params):
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 16, (2, 5)), jnp.int32)
        greedy = model.generate(params, prompt, 6, temperature=0.0)
        beams, scores = model.beam_search(params, prompt, 6, beam_size=1)
        np.testing.assert_array_equal(np.asarray(beams[:, 0]),
                                      np.asarray(greedy))
        assert scores.shape == (2, 1)

    def test_top_beam_beats_or_matches_greedy(self, model, params):
        """The width-4 top beam's sequence log-prob must be >= greedy's
        (beam search explores a superset of greedy's path)."""
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, 16, (1, 4)), jnp.int32)
        greedy = model.generate(params, prompt, 5, temperature=0.0)
        beams, _ = model.beam_search(params, prompt, 5, beam_size=4)
        g = seq_logprob(model, params, np.asarray(greedy[0]), 4)
        b = seq_logprob(model, params, np.asarray(beams[0, 0]), 4)
        assert b >= g - 1e-4

    def test_matches_exhaustive_search(self, model, params):
        """Width >= V^n is exact: the top beam must equal the argmax over
        ALL 16^2 continuations of a 2-token extension."""
        prompt = jnp.asarray([[3, 7, 1]], jnp.int32)
        beams, scores = model.beam_search(params, prompt, 2, beam_size=16)
        best_score, best_seq = -1e30, None
        for a in range(16):
            for c in range(16):
                seq = np.concatenate([np.asarray(prompt[0]), [a, c]])
                s = seq_logprob(model, params, seq, 3)
                if s > best_score:
                    best_score, best_seq = s, seq
        np.testing.assert_array_equal(np.asarray(beams[0, 0]), best_seq)
        assert float(scores[0, 0]) == pytest.approx(best_score, abs=1e-3)

    def test_scores_sorted_and_consistent(self, model, params):
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(0, 16, (2, 4)), jnp.int32)
        beams, scores = model.beam_search(params, prompt, 4, beam_size=3)
        s = np.asarray(scores)
        assert (np.diff(s, axis=-1) <= 1e-6).all()     # descending
        # each reported score == the sequence's actual log-prob
        for bi in range(2):
            for wi in range(3):
                actual = seq_logprob(model, params,
                                     np.asarray(beams[bi, wi]), 4)
                assert float(s[bi, wi]) == pytest.approx(actual, abs=1e-3)

    def test_eos_freezes_beam(self, model, params):
        """After a beam emits EOS, every later position is EOS and its
        score stops changing."""
        prompt = jnp.asarray([[2, 9]], jnp.int32)
        beams, scores = model.beam_search(params, prompt, 8, beam_size=16,
                                          eos_id=0)
        found = False
        for wi in range(16):
            gen = np.asarray(beams[0, wi, 2:])
            eos_pos = np.where(gen == 0)[0]
            if len(eos_pos) and eos_pos[0] < len(gen) - 1:
                assert (gen[eos_pos[0]:] == 0).all()
                found = True
        assert found, "no beam finished with EOS mid-sequence"

    def test_prompt_preserved_all_beams(self, model, params):
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, 16, (2, 6)), jnp.int32)
        beams, _ = model.beam_search(params, prompt, 3, beam_size=4)
        np.testing.assert_array_equal(
            np.asarray(beams[:, :, :6]),
            np.repeat(np.asarray(prompt)[:, None], 4, axis=1))

    def test_under_jit(self, model, params):
        prompt = jnp.zeros((1, 4), jnp.int32)
        f = jax.jit(lambda p, t: model.beam_search(p, t, 4, beam_size=2))
        beams, scores = f(params, prompt)
        assert beams.shape == (1, 2, 8)
        assert np.isfinite(np.asarray(scores)).all()


class TestFusedBeamSearch:
    """Beam search through the fused decode stack kernel
    (ops/decode_kernel.py): the W beams are W kernel streams; all beam
    bookkeeping (top-W, cache-row reordering) stays outside the kernel.
    Interpret mode on CPU; fp32 tiny configs give near-exact logit parity,
    so tokens AND scores must match the unfused path."""

    def test_matches_unfused(self, model, params):
        prompt = jnp.asarray(
            np.random.default_rng(4).integers(0, 16, (2, 5)), jnp.int32)
        ref, ref_s = model.beam_search(params, prompt, 6, beam_size=4)
        got, got_s = model.beam_search(params, prompt, 6, beam_size=4,
                                       fused=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        np.testing.assert_allclose(np.asarray(got_s), np.asarray(ref_s),
                                   atol=1e-4)

    def test_beam1_equals_fused_greedy(self, model, params):
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(0, 16, (1, 5)), jnp.int32)
        greedy = model.generate(params, prompt, 6, temperature=0.0,
                                fused=True)
        beams, _ = model.beam_search(params, prompt, 6, beam_size=1,
                                     fused=True)
        np.testing.assert_array_equal(np.asarray(beams[:, 0]),
                                      np.asarray(greedy))

    def test_matches_exhaustive_search(self):
        """Exact optimality inside the kernel's 8-stream cap: with V=8 and
        W=8 (= V), width-W beam search IS exhaustive over the 8^2
        two-token continuations — the fused top beam must equal the brute-
        force argmax, like the unfused W=V test above."""
        m = GPT(GPTConfig.tiny(vocab_size=8, dim=16, num_heads=2,
                               mlp_dim=32, max_len=32))
        p = m.init(jax.random.key(2))
        prompt = jnp.asarray([[3, 7, 1]], jnp.int32)
        beams, scores = m.beam_search(p, prompt, 2, beam_size=8, fused=True)
        best_score, best_seq = -1e30, None
        for a in range(8):
            for c in range(8):
                seq = np.concatenate([np.asarray(prompt[0]), [a, c]])
                s = seq_logprob(m, p, seq, 3)
                if s > best_score:
                    best_score, best_seq = s, seq
        np.testing.assert_array_equal(np.asarray(beams[0, 0]), best_seq)
        assert float(scores[0, 0]) == pytest.approx(best_score, abs=1e-3)

    def test_eos_freezes_beam_fused(self, model, params):
        prompt = jnp.asarray([[2, 9]], jnp.int32)
        beams, scores = model.beam_search(params, prompt, 8, beam_size=8,
                                          eos_id=0, fused=True)
        ref, ref_s = model.beam_search(params, prompt, 8, beam_size=8,
                                       eos_id=0)
        np.testing.assert_array_equal(np.asarray(beams), np.asarray(ref))
        np.testing.assert_allclose(np.asarray(scores), np.asarray(ref_s),
                                   atol=1e-4)

    def test_int8_composes(self, model, params):
        """int8-quantized weights through the fused beam path: valid
        shapes, finite sorted scores (bit-parity with fp is not expected
        at int8)."""
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        beams, scores = model.beam_search(params, prompt, 4, beam_size=4,
                                          fused=True, int8_weights=True)
        assert beams.shape == (1, 4, 8)
        s = np.asarray(scores)
        assert np.isfinite(s).all()
        assert (np.diff(s, axis=-1) <= 1e-6).all()

    def test_stream_cap_enforced(self, model, params):
        prompt = jnp.zeros((2, 4), jnp.int32)
        with pytest.raises(ValueError, match="capped at"):
            model.beam_search(params, prompt, 4, beam_size=24, fused=True)
        # beyond one sublane tile, B*W must be a multiple of 8
        with pytest.raises(ValueError, match="multiple of the sublane"):
            model.beam_search(params, prompt, 4, beam_size=6, fused=True)

    def test_two_prompts_beam8_tiled_matches_unfused(self, model, params):
        """B=2 x W=8 = 16 streams: the fused beam rides two sublane tiles
        and must match the unfused beam exactly."""
        prompt = jnp.asarray(
            np.random.default_rng(6).integers(0, 16, (2, 4)), jnp.int32)
        ref, ref_s = model.beam_search(params, prompt, 5, beam_size=8)
        got, got_s = model.beam_search(params, prompt, 5, beam_size=8,
                                       fused=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        np.testing.assert_allclose(np.asarray(got_s), np.asarray(ref_s),
                                   atol=1e-4)

    def test_under_jit(self, model, params):
        prompt = jnp.asarray([[5, 11, 2, 8]], jnp.int32)
        f = jax.jit(lambda p, t: model.beam_search(p, t, 4, beam_size=4,
                                                   fused=True))
        beams, scores = f(params, prompt)
        ref, _ = model.beam_search(params, prompt, 4, beam_size=4)
        np.testing.assert_array_equal(np.asarray(beams), np.asarray(ref))
