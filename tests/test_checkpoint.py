"""Checkpoint/resume tests (new capability; the reference lost all state on
crash — SURVEY.md §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import ClusterConfig, TrainConfig
from dtf_tpu.data import load_mnist
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.train.checkpoint import CheckpointManager
from dtf_tpu.train.trainer import Trainer, init_state


class TestCheckpointManager:
    def test_save_restore_roundtrip(self, mesh8, tmp_path):
        model = MnistMLP(init_scale="fan_in")
        opt = optim.momentum(0.1)
        state = init_state(model, opt, seed=1, mesh=mesh8)
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
        mgr.save(5, state, force=True)
        mgr.wait()
        assert mgr.latest_step() == 5

        template = init_state(model, opt, seed=2, mesh=mesh8)  # different values
        restored, step = mgr.restore(template)
        assert step == 5
        np.testing.assert_array_equal(np.asarray(restored["params"]["l1"]["w"]),
                                      np.asarray(state["params"]["l1"]["w"]))
        # shardings preserved from template
        assert restored["params"]["l1"]["w"].sharding.is_fully_replicated
        mgr.close()

    def test_restore_empty_dir_returns_template(self, mesh8, tmp_path):
        model = MnistMLP(init_scale="fan_in")
        state = init_state(model, optim.sgd(0.1), seed=1, mesh=mesh8)
        mgr = CheckpointManager(str(tmp_path / "empty"), async_save=False)
        restored, step = mgr.restore(state)
        assert step is None and restored is state
        mgr.close()

    def test_cpu_save_never_aliases_live_buffers(self, mesh8, tmp_path):
        """On the CPU backend orbax's transfer-to-host is zero-copy, so an
        async save of the LIVE (donated) train state can serialize bytes
        the next dispatched step already overwrote — a torn checkpoint
        whose label-N tree holds step-N+1 values (caught in the wild by
        the scenario matrix's elastic cell; the CRC manifest can't see it
        because it checksums whatever landed).  Pin the fix: the tree
        handed to orbax must be a SNAPSHOT, sharing no buffer with the
        caller's state."""
        model = MnistMLP(init_scale="fan_in")
        state = init_state(model, optim.sgd(0.1), seed=1, mesh=mesh8)
        mgr = CheckpointManager(str(tmp_path / "snap"), async_save=True)
        captured = {}
        real_save = mgr._mgr.save

        def spy(step, args=None, force=False):
            captured["tree"] = args.item
            return real_save(step, args=args, force=force)

        mgr._mgr.save = spy
        mgr.save(3, state, force=True)
        mgr.wait()
        assert "tree" in captured

        def ptrs(tree):
            return {s.data.unsafe_buffer_pointer()
                    for x in jax.tree_util.tree_leaves(tree)
                    if isinstance(x, jax.Array)
                    for s in x.addressable_shards}

        live, saved = ptrs(state), ptrs(captured["tree"])
        assert live and saved
        assert not (live & saved), "saved tree aliases live state buffers"
        # and the snapshot really landed with the right contents
        template = init_state(model, optim.sgd(0.1), seed=2, mesh=mesh8)
        restored, step = mgr.restore(template)
        assert step == 3
        np.testing.assert_array_equal(
            np.asarray(restored["params"]["l1"]["w"]),
            np.asarray(state["params"]["l1"]["w"]))
        mgr.close()


class TestTrainerResume:
    def test_crash_resume_continues_trajectory(self, mesh8, tmp_path):
        """Train 1 of 2 epochs w/ checkpoints, 'crash', resume with a fresh
        process (fresh data cursor): the resumed run must CONTINUE the
        interrupted trajectory — same batches, same per-step rngs, same
        final params as an uninterrupted 2-epoch run."""
        cfg = TrainConfig(batch_size=64, learning_rate=0.05, epochs=2,
                          log_frequency=1000, seed=1, logdir=str(tmp_path / "a"),
                          checkpoint_every=50)
        cluster = Cluster(config=ClusterConfig(), mesh=mesh8)

        t1 = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                     cfg)
        r1 = t1.fit(load_mnist(seed=1), epochs=1)    # "crash" after epoch 1
        t1.ckpt.close()
        steps_done = r1["steps"]
        assert steps_done > 0

        cfg2 = TrainConfig(**{**cfg.__dict__, "resume": True})
        t2 = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                     cfg2)
        assert int(t2.state["step"]) == steps_done   # resumed, not reinit
        r2 = t2.fit(load_mnist(seed=1), epochs=2)    # trains ONLY epoch 2
        t2.ckpt.close()
        assert r2["steps"] == steps_done * 2

        # uninterrupted 2-epoch baseline, same seeds
        cfg_b = TrainConfig(batch_size=64, learning_rate=0.05, epochs=2,
                            log_frequency=1000, seed=1,
                            logdir=str(tmp_path / "b"))
        tb = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                     cfg_b)
        rb = tb.fit(load_mnist(seed=1), epochs=2)
        assert rb["steps"] == r2["steps"]
        for a, b in zip(jax.tree_util.tree_leaves(t2.state["params"]),
                        jax.tree_util.tree_leaves(tb.state["params"])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6)

    def test_second_fit_same_dataset_continues(self, mesh8, tmp_path):
        """Same-session continue-training: fit(1 epoch) then fit(2 epochs)
        on the SAME dataset must train exactly one more epoch without
        double-advancing the data cursor."""
        cfg = TrainConfig(batch_size=128, learning_rate=0.05, epochs=1,
                          log_frequency=1000, seed=1, logdir=str(tmp_path))
        cluster = Cluster(config=ClusterConfig(), mesh=mesh8)
        splits = load_mnist(seed=1)
        t = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                    cfg)
        r1 = t.fit(splits, epochs=1)
        consumed_after_1 = splits.train.batches_consumed
        assert consumed_after_1 == r1["steps"]
        r2 = t.fit(splits, epochs=2)
        assert r2["steps"] == 2 * r1["steps"]
        # cursor advanced exactly one more epoch, no replay double-advance
        assert splits.train.batches_consumed == 2 * consumed_after_1

    def test_resume_past_budget_is_noop(self, mesh8, tmp_path):
        """Resuming a finished run trains zero extra steps."""
        cfg = TrainConfig(batch_size=128, learning_rate=0.05, epochs=1,
                          log_frequency=1000, seed=1, logdir=str(tmp_path),
                          checkpoint_every=50)
        cluster = Cluster(config=ClusterConfig(), mesh=mesh8)
        t1 = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                     cfg)
        r1 = t1.fit(load_mnist(seed=1))
        t1.ckpt.close()

        cfg2 = TrainConfig(**{**cfg.__dict__, "resume": True})
        t2 = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                     cfg2)
        r2 = t2.fit(load_mnist(seed=1))
        t2.ckpt.close()
        assert r2["steps"] == r1["steps"]
        assert not np.isnan(r2["test_accuracy"])
