"""Fused decode stack-kernel parity vs the op-per-op decode loop.

Runs in pallas interpret mode on the CPU rig (the kernel auto-detects
non-TPU backends); real-chip numbers live in BASELINE.md.  The fused path
computes in the params' dtype, so fp32 tiny configs give near-exact parity
with the unfused loop."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.models.gpt import GPT, GPTConfig


def mk(seed=0, **kw):
    cfg = GPTConfig.tiny(**kw)
    m = GPT(cfg)
    return m, m.init(jax.random.key(seed))


def prompt_of(m, b=1, p=8, seed=1):
    return jax.random.randint(jax.random.key(seed), (b, p), 0,
                              m.cfg.vocab_size)


class TestFusedDecode:
    def test_greedy_matches_unfused(self):
        m, p = mk()
        pr = prompt_of(m)
        a = m.generate(p, pr, 12, temperature=0.0)
        b = m.generate(p, pr, 12, temperature=0.0, fused=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sampled_matches_unfused_same_rng(self):
        """Identical rng stream + near-identical logits -> identical
        samples (the fused loop mirrors generate()'s split order)."""
        m, p = mk()
        pr = prompt_of(m)
        kw = dict(temperature=0.9, top_k=8, rng=jax.random.key(5))
        a = m.generate(p, pr, 10, **kw)
        b = m.generate(p, pr, 10, fused=True, **kw)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_gqa_swiglu_variant(self):
        """Grouped-query attention + SwiGLU (the LLaMA-style decode
        config) through the fused kernel."""
        m, p = mk(num_kv_heads=2, mlp_act="swiglu")
        pr = prompt_of(m)
        a = m.generate(p, pr, 10, temperature=0.0)
        b = m.generate(p, pr, 10, temperature=0.0, fused=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_int8_fused_matches_fp(self):
        """int8 weights inside the kernel: greedy output nearly identical
        to the fp fused path (~0.4% per-channel rounding; once one token
        flips the tails diverge, so assert a long identical prefix and
        high overall agreement — the at-scale perplexity contract lives
        in BASELINE.md)."""
        m, p = mk()
        pr = prompt_of(m)
        a = np.asarray(m.generate(p, pr, 16, temperature=0.0, fused=True))
        b = np.asarray(m.generate(p, pr, 16, temperature=0.0, fused=True,
                                  int8_weights=True))
        gen_a, gen_b = a[0, pr.shape[1]:], b[0, pr.shape[1]:]
        # A tiny random model has near-uniform logits, so once one token
        # flips the tails diverge chaotically; the falsifiable claim is
        # the long identical prefix.
        assert np.array_equal(gen_a[:8], gen_b[:8])

    def test_eos_pinning(self):
        m, p = mk()
        pr = prompt_of(m)
        out = m.generate(p, pr, 10, temperature=0.0, eos_id=3, fused=True)
        gen = np.asarray(out)[0, pr.shape[1]:]
        hits = np.where(gen == 3)[0]
        if hits.size:                      # everything after first EOS is EOS
            assert np.all(gen[hits[0]:] == 3)

    def test_batched_matches_unfused(self):
        """B=4 streams through one kernel (leading-dim batching): every
        stream's greedy output must match the unfused loop's."""
        m, p = mk()
        pr = prompt_of(m, b=4)
        a = m.generate(p, pr, 10, temperature=0.0)
        b = m.generate(p, pr, 10, temperature=0.0, fused=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_batched_llama_style_int8(self):
        """The batched kernel branch with EVERY option stacked: GQA lane
        expansion + in-kernel RoPE + SwiGLU + int8 weights, B=4 — guards
        batch>1 interactions the single-stream tests never reach."""
        m, p = mk(rope=True, num_kv_heads=2, mlp_act="swiglu")
        pr = prompt_of(m, b=4)
        a = np.asarray(m.generate(p, pr, 10, temperature=0.0))
        b = np.asarray(m.generate(p, pr, 10, temperature=0.0, fused=True))
        np.testing.assert_array_equal(a, b)
        # int8 fused runs and matches its own fp-fused prefix (cf.
        # test_int8_fused_matches_fp for the rounding caveat)
        c = np.asarray(m.generate(p, pr, 10, temperature=0.0, fused=True,
                                  int8_weights=True))
        assert np.array_equal(b[:, 8:12], c[:, 8:12])

    def test_stream_count_rules(self):
        """Streams beyond one sublane tile must be a multiple of 8; the
        hard cap is MAX_FUSED_STREAMS."""
        from dtf_tpu.ops.decode_kernel import MAX_FUSED_STREAMS

        m, p = mk()
        with pytest.raises(ValueError, match="multiple of the sublane"):
            m.generate(p, prompt_of(m, b=9), 4, fused=True)
        with pytest.raises(ValueError, match="capped at"):
            m.generate(p, prompt_of(m, b=MAX_FUSED_STREAMS + 8), 4,
                       fused=True)

    def test_batch16_tiled_matches_unfused(self):
        """16 streams ride two sublane tiles on the inner grid dim; greedy
        tokens must match the unfused loop stream-for-stream."""
        m, p = mk()
        pr = prompt_of(m, b=16)
        a = m.generate(p, pr, 8, temperature=0.0)
        b = m.generate(p, pr, 8, temperature=0.0, fused=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_batch32_tiled_gqa_matches_unfused(self):
        """The full cap (32 streams, four tiles) with the LLaMA-style
        wiring (RoPE + GQA + SwiGLU)."""
        m, p = mk(rope=True, num_kv_heads=2, mlp_act="swiglu")
        pr = prompt_of(m, b=32)
        a = m.generate(p, pr, 6, temperature=0.0)
        b = m.generate(p, pr, 6, temperature=0.0, fused=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rope_llama_style_matches_unfused(self):
        """Full LLaMA-style wiring (RoPE in-kernel via the swap-halves
        constant matmul + GQA + SwiGLU) through the fused kernel."""
        m, p = mk(rope=True, num_kv_heads=2, mlp_act="swiglu")
        pr = prompt_of(m)
        a = m.generate(p, pr, 10, temperature=0.0)
        b = m.generate(p, pr, 10, temperature=0.0, fused=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestInt8KVCache:
    """int8 KV-cache rows through the fused kernel (quantize_rows +
    in-kernel per-row dequant via the lane-0 selector matmul): halves the
    per-token cache DMA, the dominant traffic at batched long-context
    decode.  Quality contract lives in bench.int8_quality.kv_run."""

    def test_quantize_rows_roundtrip(self):
        from dtf_tpu.ops.decode_kernel import quantize_rows

        x = jax.random.normal(jax.random.key(0), (4, 16, 96),
                              jnp.float32) * 3.0
        q, sc = quantize_rows(x)
        assert q.dtype == jnp.int8 and sc.shape == (4, 16, 8)
        # lane-replicated scale: all 8 lanes identical
        np.testing.assert_array_equal(np.asarray(sc),
                                      np.asarray(sc[..., :1]) *
                                      np.ones((1, 1, 8), np.float32))
        back = q.astype(jnp.float32) * sc[..., :1]
        err = np.abs(np.asarray(back - x))
        bound = np.asarray(jnp.max(jnp.abs(x), -1, keepdims=True)) / 127
        assert (err <= bound + 1e-6).all()

    def test_greedy_agreement_with_fp_cache(self):
        """Random-init tiny logits are near-uniform, so token flips are
        expected — require a long identical prefix and high agreement
        (same contract as the int8-weights test)."""
        m, p = mk()
        pr = prompt_of(m)
        a = m.generate(p, pr, 16, temperature=0.0, fused=True)
        b = m.generate(p, pr, 16, temperature=0.0, fused=True,
                       kv_int8=True)
        an, bn = np.asarray(a)[0, 8:], np.asarray(b)[0, 8:]
        agree = (an == bn).mean()
        assert agree >= 0.5, agree
        assert (an[:4] == bn[:4]).all()

    def test_batched_tiles_and_gqa(self):
        m, p = mk(rope=True, num_kv_heads=2, mlp_act="swiglu")
        pr = prompt_of(m, b=16)
        out = m.generate(p, pr, 6, temperature=0.0, fused=True,
                         kv_int8=True)
        assert out.shape == (16, 14)

    def test_beam_composes(self):
        m, p = mk()
        pr = prompt_of(m)
        beams, scores = m.beam_search(p, pr, 5, beam_size=4, fused=True,
                                      kv_int8=True)
        assert beams.shape == (1, 4, 13)
        assert np.isfinite(np.asarray(scores)).all()

    def test_requires_fused(self):
        m, p = mk()
        pr = prompt_of(m)
        with pytest.raises(ValueError, match="fused"):
            m.generate(p, pr, 4, kv_int8=True)
        with pytest.raises(ValueError, match="fused"):
            m.beam_search(p, pr, 4, beam_size=2, kv_int8=True)

    def test_scale_mismatch_rejected(self):
        from dtf_tpu.ops.decode_kernel import (fused_decode_pack,
                                               fused_decode_step)

        m, p = mk()
        pack = fused_decode_pack(p, m.cfg)
        ck = jnp.zeros((2, 1, 16, 32), jnp.int8)
        x = jnp.zeros((1, 32), jnp.float32)
        with pytest.raises(ValueError, match="int8 caches require"):
            fused_decode_step(pack, ck, ck, x, 4, m.cfg)


class TestChunkedCache:
    """Long-context cache chunking: a third (innermost) grid dim walks
    the KV cache with an online softmax (`_decode_kernel_chunked`), so
    caches beyond the per-block VMEM budget stay on the fused path."""

    def test_kernel_matches_single_chunk(self):
        """The chunked online softmax equals the one-shot kernel to fp32
        roundoff on raw caches."""
        from dtf_tpu.ops.decode_kernel import (fused_decode_pack,
                                               fused_decode_step)

        m, p = mk()
        pack = fused_decode_pack(p, m.cfg)
        L, b, T, kn = 2, 2, 64, 32
        ck = jax.random.normal(jax.random.key(1), (L, b, T, kn),
                               jnp.float32) * 0.3
        cv = jax.random.normal(jax.random.key(2), (L, b, T, kn),
                               jnp.float32) * 0.3
        x = jax.random.normal(jax.random.key(3), (b, 32), jnp.float32)
        ref = fused_decode_step(pack, ck, cv, x, 37, m.cfg)
        got = fused_decode_step(pack, ck, cv, x, 37, m.cfg,
                                cache_chunk=16)
        for r_, g_ in zip(ref, got):
            np.testing.assert_allclose(np.asarray(r_, np.float32),
                                       np.asarray(g_, np.float32),
                                       atol=1e-5)

    def test_generate_matches_unfused(self):
        m, p = mk()
        pr = prompt_of(m, b=2)
        ref = m.generate(p, pr, 20, temperature=0.0)
        got = m.generate(p, pr, 20, temperature=0.0, fused=True,
                         cache_chunk=16)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_composes_with_gqa_rope_kvint8_beam(self):
        m, p = mk(rope=True, num_kv_heads=2, mlp_act="swiglu")
        pr = prompt_of(m, b=2)
        out = m.generate(p, pr, 12, temperature=0.0, fused=True,
                         cache_chunk=8, kv_int8=True)
        assert out.shape == (2, 20)
        m2, p2 = mk()
        beams, scores = m2.beam_search(p2, prompt_of(m2), 6, beam_size=4,
                                       fused=True, cache_chunk=16)
        ref, _ = m2.beam_search(p2, prompt_of(m2), 6, beam_size=4,
                                fused=True)
        np.testing.assert_array_equal(np.asarray(beams), np.asarray(ref))

    def test_bad_chunk_rejected(self):
        from dtf_tpu.ops.decode_kernel import (fused_decode_pack,
                                               fused_decode_step)

        m, p = mk()
        pack = fused_decode_pack(p, m.cfg)
        ck = jnp.zeros((2, 1, 64, 32), jnp.float32)
        x = jnp.zeros((1, 32), jnp.float32)
        for bad in (48,    # not a divisor of T=64
                    4):    # divides 64 but is not 8-aligned
            with pytest.raises(ValueError, match="cache_chunk"):
                fused_decode_step(pack, ck, ck, x, 4, m.cfg,
                                  cache_chunk=bad)
