"""GPT causal LM: causality, loss, DP training, KV-cache generation
consistency with the parallel forward pass."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.models.gpt import GPT, GPTConfig


@pytest.fixture(scope="module")
def tiny():
    return GPT(GPTConfig.tiny())


@pytest.fixture(scope="module")
def tiny_params(tiny):
    return tiny.init(jax.random.key(0))


class TestGPTModel:
    def test_logits_shape(self, tiny, tiny_params):
        toks = jnp.zeros((2, 16), jnp.int32)
        logits = tiny.apply(tiny_params, toks)
        assert logits.shape == (2, 16, 128)
        assert logits.dtype == jnp.float32

    def test_causality(self, tiny, tiny_params):
        """Changing a future token must not change past logits."""
        rng = np.random.default_rng(0)
        a = rng.integers(0, 128, (1, 16)).astype(np.int32)
        b = a.copy()
        b[0, 10:] = rng.integers(0, 128, 6)
        la = tiny.apply(tiny_params, jnp.asarray(a))
        lb = tiny.apply(tiny_params, jnp.asarray(b))
        np.testing.assert_allclose(la[0, :10], lb[0, :10], atol=1e-5)
        assert not np.allclose(la[0, 10:], lb[0, 10:])

    @pytest.mark.parametrize("chunk,smoothing", [(8, 0.0), (7, 0.1)])
    def test_chunked_loss_matches_dense(self, tiny_params, chunk, smoothing):
        """loss_chunk must be a pure memory optimization: loss, metrics,
        AND gradients identical to the dense head (chunk 7 exercises the
        pad/weight path on T-1 = 15)."""
        dense = GPT(GPTConfig.tiny(label_smoothing=smoothing))
        chunked = GPT(GPTConfig.tiny(label_smoothing=smoothing,
                                     loss_chunk=chunk))
        toks = jnp.asarray(np.random.default_rng(3).integers(
            0, 128, (4, 16)), jnp.int32)
        (l_d, m_d), g_d = jax.value_and_grad(
            lambda p: dense.loss(p, toks), has_aux=True)(tiny_params)
        (l_c, m_c), g_c = jax.value_and_grad(
            lambda p: chunked.loss(p, toks), has_aux=True)(tiny_params)
        np.testing.assert_allclose(l_c, l_d, rtol=1e-6)
        for k in m_d:
            np.testing.assert_allclose(m_c[k], m_d[k], rtol=1e-5,
                                       err_msg=k)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5),
            g_c, g_d)

    def test_pipelined_decoder_matches_scan(self, tiny_params):
        """GPipe over the decoder stack: loss and grads equal the
        lax.scan path."""
        from dtf_tpu.parallel.mesh import make_mesh

        mesh = make_mesh("data=4,pipe=2")
        pp = GPT(GPTConfig.tiny(pipeline_mesh=mesh,
                                pipeline_microbatches=2))
        seq = GPT(GPTConfig.tiny())
        toks = jnp.asarray(np.random.default_rng(4).integers(
            0, 128, (16, 16)), jnp.int32)
        (l_p, _), g_p = jax.value_and_grad(
            lambda p: pp.loss(p, toks), has_aux=True)(tiny_params)
        (l_s, _), g_s = jax.value_and_grad(
            lambda p: seq.loss(p, toks), has_aux=True)(tiny_params)
        np.testing.assert_allclose(l_p, l_s, rtol=1e-6)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, atol=2e-5),
            g_p, g_s)

    def test_int8_decode_weights_close_to_fp(self, tiny, tiny_params):
        """Per-channel int8 decode weights: quantization error bounded and
        the greedy generation stays token-identical to fp on a tiny model
        (a well-separated argmax survives ~0.4%-per-channel rounding)."""
        from dtf_tpu.models.gpt import _quantize_cols

        w = tiny_params["layers"]["fc1"]["w"]
        q, scale = _quantize_cols(w)
        deq = q.astype(jnp.float32) * scale
        err = jnp.max(jnp.abs(deq - w)) / jnp.max(jnp.abs(w))
        assert float(err) < 0.005, float(err)

        prompt = jnp.asarray(np.random.default_rng(6).integers(
            0, 128, (2, 8)), jnp.int32)
        fp = tiny.generate(tiny_params, prompt, 16, temperature=0.0)
        q8 = tiny.generate(tiny_params, prompt, 16, temperature=0.0,
                           int8_weights=True)
        agree = float(jnp.mean((fp == q8).astype(jnp.float32)))
        assert agree > 0.9, agree      # rare argmax ties may flip

    def test_int8_decode_llama_options(self):
        """The int8 path through the SwiGLU gate, GQA o-proj reshape, and
        RoPE: per-step decode logits nearly identical to fp.  (Trajectory
        agreement is the wrong metric at random init — near-tied argmax
        flips once and the continuation diverges chaotically.)"""
        model = GPT(GPTConfig.tiny(rope=True, num_kv_heads=2,
                                   mlp_act="swiglu"))
        p = model.init(jax.random.key(9))
        prompt = jnp.asarray(np.random.default_rng(10).integers(
            0, 128, (2, 8)), jnp.int32)
        cache, _ = model._prefill_cache(p, prompt, model._cache_len(32))
        tok = prompt[:, -1:]
        pos = jnp.int32(8)
        lf, _ = model._decode_logits(p, cache, tok, pos,
                                     model._decode_pack(p))
        lq, _ = model._decode_logits(p, cache, tok, pos,
                                     model._decode_pack(p, int8=True))
        cos = (jnp.sum(lf * lq, -1)
               / (jnp.linalg.norm(lf, axis=-1)
                  * jnp.linalg.norm(lq, axis=-1)))
        assert float(cos.min()) > 0.999, np.asarray(cos)
        # beam search takes the same container end to end
        _, scores = model.beam_search(p, prompt, 8, beam_size=2,
                                      int8_weights=True)
        assert bool(jnp.all(jnp.isfinite(scores)))

    def test_1f1b_grads_match_dense_path(self, tiny_params):
        """GPT's 1F1B pipeline (pipeline_loss_and_grads) must reproduce
        the dense jax.grad loss and gradients."""
        from dtf_tpu.parallel.mesh import make_mesh

        mesh = make_mesh("data=4,pipe=2")
        pp = GPT(GPTConfig.tiny(pipeline_mesh=mesh,
                                pipeline_microbatches=4,
                                pipeline_schedule="1f1b"))
        dense = GPT(GPTConfig.tiny())
        toks = jnp.asarray(np.random.default_rng(5).integers(
            0, 128, (16, 16)), jnp.int32)
        loss1, _, g1 = pp.pipeline_loss_and_grads(tiny_params,
                                                  {"tokens": toks})
        (loss2, _), g2 = jax.value_and_grad(
            lambda p: dense.loss(p, toks), has_aux=True)(tiny_params)
        np.testing.assert_allclose(loss1, loss2, rtol=1e-5)
        flat1 = jax.tree_util.tree_leaves_with_path(g1)
        flat2 = dict(jax.tree_util.tree_leaves_with_path(g2))
        for path, leaf in flat1:
            np.testing.assert_allclose(
                leaf, flat2[path], atol=3e-5,
                err_msg=jax.tree_util.keystr(path))

    def test_loss_decreases_in_training(self, tiny, mesh8):
        from dtf_tpu import optim
        from dtf_tpu.data.datasets import synthetic_text
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        opt = optim.adam(1e-3)
        state = init_state(tiny, opt, seed=0, mesh=mesh8)
        step = make_train_step(tiny.loss, opt, mesh8, donate=False)
        toks = synthetic_text(16, 32, 128, seed=1)
        batch = put_global_batch(mesh8, toks)
        losses = []
        for i in range(8):
            state, m = step(state, batch, jax.random.key(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
        assert np.isfinite(m["perplexity"])

    def test_unrolled_layer_loop_matches_scan(self):
        """GPT's layer_loop='unroll' + remat_policy='attn' (the
        benchmark-fast path) must produce the scanned default's loss and
        gradients."""
        ms = GPT(GPTConfig.tiny())
        mu = GPT(GPTConfig.tiny(layer_loop="unroll", remat=True,
                                remat_policy="attn"))
        p = ms.init(jax.random.key(0))
        toks = jnp.asarray(
            np.random.default_rng(3).integers(0, 128, (2, 16)), jnp.int32)
        (ls, _), gs = jax.value_and_grad(
            lambda q: ms.loss(q, toks), has_aux=True)(p)
        (lu, _), gu = jax.value_and_grad(
            lambda q: mu.loss(q, toks), has_aux=True)(p)
        assert float(ls) == pytest.approx(float(lu), rel=1e-6)
        for (path, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(gs),
                jax.tree_util.tree_leaves_with_path(gu)):
            np.testing.assert_allclose(a, b, atol=1e-5,
                                       err_msg=jax.tree_util.keystr(path))

    def test_remat_matches(self):
        cfg_a, cfg_b = GPTConfig.tiny(), GPTConfig.tiny(remat=True)
        ma, mb = GPT(cfg_a), GPT(cfg_b)
        params = ma.init(jax.random.key(1))
        toks = jnp.asarray(
            np.random.default_rng(2).integers(0, 128, (2, 16)), jnp.int32)
        la, _ = ma.loss(params, toks)
        lb, _ = mb.loss(params, toks)
        assert float(la) == pytest.approx(float(lb), abs=1e-6)


class TestGeneration:
    def test_greedy_matches_parallel_forward(self, tiny, tiny_params):
        """KV-cache decode must reproduce the parallel forward's argmax
        continuation token-for-token (greedy, temperature=0)."""
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, 128, (2, 8)), jnp.int32)
        out = tiny.generate(tiny_params, prompt, max_new_tokens=6,
                            temperature=0.0)
        assert out.shape == (2, 14)
        np.testing.assert_array_equal(out[:, :8], prompt)
        # replay: each generated token == argmax of the parallel forward
        for t in range(8, 14):
            logits = tiny.apply(tiny_params, out[:, :t])
            np.testing.assert_array_equal(
                np.asarray(jnp.argmax(logits[:, -1], -1), np.int32),
                np.asarray(out[:, t]))

    def test_sampling_deterministic_per_key(self, tiny, tiny_params):
        prompt = jnp.zeros((1, 4), jnp.int32)
        a = tiny.generate(tiny_params, prompt, 8, temperature=1.0,
                          rng=jax.random.key(7))
        b = tiny.generate(tiny_params, prompt, 8, temperature=1.0,
                          rng=jax.random.key(7))
        c = tiny.generate(tiny_params, prompt, 8, temperature=1.0,
                          rng=jax.random.key(8))
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_generate_under_jit(self, tiny, tiny_params):
        prompt = jnp.zeros((1, 4), jnp.int32)
        gen = jax.jit(lambda p, t: tiny.generate(p, t, 4, temperature=0.0))
        out = gen(tiny_params, prompt)
        assert out.shape == (1, 8)

    def test_overflow_raises(self, tiny, tiny_params):
        with pytest.raises(ValueError, match="max_len"):
            tiny.generate(tiny_params, jnp.zeros((1, 60), jnp.int32), 10)

    def test_fused_unaligned_window_fails_fast(self):
        """With a non-8-aligned max_len and a total in (floor8(max_len),
        max_len], no 8-aligned cache length exists; fused decode must
        raise the clear precondition error, not fail deep in the
        kernel (ADVICE r4)."""
        from dtf_tpu.models.gpt import GPT, GPTConfig
        model = GPT(GPTConfig.tiny(max_len=59))
        params = model.init(jax.random.key(0))
        assert model._cache_len(58) == 59        # the unavoidable odd T
        with pytest.raises(ValueError, match="8-aligned"):
            model.generate(params, jnp.zeros((1, 50), jnp.int32), 8,
                           fused=True, temperature=0.0)
        # unfused decode still works at the same window
        out = model.generate(params, jnp.zeros((1, 50), jnp.int32), 8,
                             temperature=0.0)
        assert out.shape == (1, 58)


class TestGenerateEdges:
    def test_max_new_tokens_zero_returns_prompt(self):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        model = GPT(GPTConfig.tiny())
        params = model.init(jax.random.key(0))
        prompt = jnp.asarray([[5, 6, 7]], jnp.int32)
        np.testing.assert_array_equal(model.generate(params, prompt, 0),
                                      prompt)

    def test_awkward_prompt_length_under_flash(self):
        """Prompt lengths with no 8-multiple divisor (e.g. 10) must prefill
        fine through the flash kernel (generate pads to a multiple of 8;
        causality keeps real positions unaffected by the pad tail)."""
        from dtf_tpu.models.gpt import GPT, GPTConfig
        flash = GPT(GPTConfig.tiny(use_flash=True))
        xla = GPT(GPTConfig.tiny(use_flash=False))
        params = flash.init(jax.random.key(0))
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 128, (2, 10)), jnp.int32)
        out_f = flash.generate(params, prompt, 4, temperature=0.0)
        out_x = xla.generate(params, prompt, 4, temperature=0.0)
        assert out_f.shape == (2, 14)
        np.testing.assert_array_equal(out_f, out_x)   # pad tail is invisible

    def test_eos_pins_finished_sequences(self):
        """With eos_id, every position after a row's first EOS is EOS.
        Small vocab makes EOS certain by construction: P(no EOS in 8x24
        uniform-ish draws over 8 tokens) ~ (7/8)^192 ~ 8e-12."""
        from dtf_tpu.models.gpt import GPT, GPTConfig
        model = GPT(GPTConfig.tiny(vocab_size=8))
        params = model.init(jax.random.key(0))
        prompt = jnp.asarray(
            np.random.default_rng(5).integers(1, 8, (8, 4)), jnp.int32)
        out = model.generate(params, prompt, 24, temperature=1.0,
                             eos_id=0, rng=jax.random.key(2))
        gen = np.asarray(out[:, 4:])
        hit = False
        for row in gen:
            eos_pos = np.where(row == 0)[0]
            if len(eos_pos):
                assert (row[eos_pos[0]:] == 0).all()
                hit = True
        assert hit, "no sequence sampled EOS (vocab 8, 24 tokens, 8 rows)"


class TestShardedDecode:
    """Multi-chip serving: generate/beam under a real mesh with
    TP-sharded weights and data-sharded prompt rows must produce the
    SAME tokens as the single-device run (GSPMD inserts the collectives;
    the op-per-op decode path is pure jnp, so sharding is a layout
    concern, not a code path).  The training-side analog is the driver's
    dryrun legs; this is the decode leg."""

    def _sharded(self, model, params, mesh):
        from dtf_tpu.parallel import sharding as sh

        shardings = sh.apply_rules(model.axes(), mesh)
        return jax.device_put(params, shardings)

    def test_generate_tp_mesh_matches_single(self, mesh_2d):
        model = GPT(GPTConfig.tiny())
        params = model.init(jax.random.key(0))
        prompt = jnp.asarray(
            np.random.default_rng(7).integers(0, 128, (4, 8)), jnp.int32)
        ref = model.generate(params, prompt, 10, temperature=0.0)

        sp = self._sharded(model, params, mesh_2d)
        from jax.sharding import NamedSharding, PartitionSpec as P
        pr = jax.device_put(prompt, NamedSharding(mesh_2d, P("data", None)))
        out = jax.jit(lambda p, t: model.generate(p, t, 10,
                                                  temperature=0.0))(sp, pr)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_generate_tp_mesh_matches_single_int8(self, mesh_2d):
        """The int8 decode pack uses the same concat-free q + stacked-kv
        layout as f32, so TP-sharded params must decode identically to
        the single-device int8 run (the concat-along-sharded-dim
        miscompile is unreachable from either pack)."""
        model = GPT(GPTConfig.tiny())
        params = model.init(jax.random.key(0))
        prompt = jnp.asarray(
            np.random.default_rng(9).integers(0, 128, (4, 8)), jnp.int32)
        ref = model.generate(params, prompt, 10, temperature=0.0,
                             int8_weights=True)
        sp = self._sharded(model, params, mesh_2d)
        from jax.sharding import NamedSharding, PartitionSpec as P
        pr = jax.device_put(prompt, NamedSharding(mesh_2d, P("data", None)))
        out = jax.jit(lambda p, t: model.generate(
            p, t, 10, temperature=0.0, int8_weights=True))(sp, pr)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_beam_tp_mesh_matches_single(self, mesh_2d):
        model = GPT(GPTConfig.tiny())
        params = model.init(jax.random.key(0))
        prompt = jnp.asarray(
            np.random.default_rng(8).integers(0, 128, (2, 6)), jnp.int32)
        ref, ref_s = model.beam_search(params, prompt, 6, beam_size=4)
        sp = self._sharded(model, params, mesh_2d)
        out, scores = jax.jit(lambda p, t: model.beam_search(
            p, t, 6, beam_size=4))(sp, prompt)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        np.testing.assert_allclose(np.asarray(scores), np.asarray(ref_s),
                                   atol=1e-4)
