"""int8-wire ring all-reduce (parallel/collectives.py, EQuARX-style):
accuracy vs exact pmean, cross-device agreement, odd sizes, and MNIST
training with compressed gradient sync."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from dtf_tpu.parallel.collectives import (
    quantized_ring_all_reduce_mean, shard_map_fn,
)
from dtf_tpu.parallel.mesh import make_mesh


def run_ring(mesh, x_global, axis="data"):
    """x_global: (n_dev, ...) — row d is device d's local value.  Returns
    the per-device all-reduce results stacked the same way."""
    fn = shard_map_fn(
        functools.partial(quantized_ring_all_reduce_mean, axis=axis),
        mesh=mesh, in_specs=P(axis), out_specs=P(axis))
    return np.asarray(fn(x_global))


class TestQuantizedRing:
    def test_close_to_exact_mean(self, mesh8):
        vals = np.random.default_rng(0).normal(size=(8, 1000)).astype(np.float32)
        out = run_ring(mesh8, jnp.asarray(vals))
        exact = vals.mean(axis=0)
        # out is (8, 1000/8)-sharded stacked back to (8, 125)? shard_map
        # out_specs=P("data") stacks device outputs along dim 0: each device
        # returns its (1, 1000) local result -> global (8, 1000).
        for d in range(8):
            seg = out[d]
            rel = np.abs(seg - exact) / (np.abs(exact).mean() + 1e-6)
            assert rel.mean() < 0.05, rel.mean()

    def test_all_devices_agree_bitwise(self, mesh8):
        vals = np.random.default_rng(1).normal(size=(8, 513)).astype(np.float32)
        out = run_ring(mesh8, jnp.asarray(vals))
        for d in range(1, 8):
            np.testing.assert_array_equal(out[0], out[d])

    def test_odd_sizes_pad_correctly(self, mesh8):
        """Sizes not divisible by n exercise the pad/unpad path."""
        for size in (1, 7, 9, 1001):
            vals = np.random.default_rng(size).normal(
                size=(8, size)).astype(np.float32)
            out = run_ring(mesh8, jnp.asarray(vals))
            exact = vals.mean(axis=0)
            assert out.shape == (8, size)
            err = np.abs(out[0] - exact).max()
            scale = np.abs(vals).max() / 127 * 8
            assert err < scale * 3, (size, err)

    def test_outlier_does_not_poison_other_blocks(self, mesh8):
        """Per-block scales: one huge outlier only coarsens ITS OWN
        256-value block.  Under a single per-chunk scale the step size
        would be outlier/127 ~ 7.9 everywhere and the small values would
        quantize to pure noise; per-block they stay accurate."""
        rng = np.random.default_rng(3)
        vals = rng.normal(size=(8, 4096)).astype(np.float32)
        vals[0, 0] = 1000.0                  # outlier in block 0
        out = run_ring(mesh8, jnp.asarray(vals))
        exact = vals.mean(axis=0)
        err = np.abs(out[0] - exact)
        # away from the outlier's block the error must be at the normal
        # per-block level (|v|~4 max -> step ~4/127 x a few hops)
        assert err[512:].max() < 0.15, err[512:].max()

    def test_zero_input_exact(self, mesh8):
        out = run_ring(mesh8, jnp.zeros((8, 64), jnp.float32))
        np.testing.assert_array_equal(out, np.zeros((8, 64)))

    def test_single_device_identity(self):
        mesh = make_mesh("data=1", devices=jax.devices()[:1])
        vals = jnp.asarray(np.random.default_rng(2).normal(
            size=(1, 32)).astype(np.float32))
        out = run_ring(mesh, vals)
        np.testing.assert_array_equal(out, np.asarray(vals))


class TestCompressedTraining:
    def test_mnist_trains_with_int8_grads(self, mesh8):
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        rng = np.random.default_rng(0)
        batch = put_global_batch(
            mesh8, (rng.random((64, 784), np.float32),
                    np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]))

        losses = {}
        for comp in (None, "int8"):
            state = init_state(model, opt, seed=1, mesh=mesh8)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_compression=comp)
            ls = []
            for i in range(10):
                state, m = step(state, batch, jax.random.key(i))
                ls.append(float(m["loss"]))
            losses[comp] = ls
        assert losses["int8"][-1] < losses["int8"][0]
        # compressed trajectory tracks the exact one loosely
        assert abs(losses["int8"][-1] - losses[None][-1]) < 0.5

    def test_convergence_ab_loss_curves_track(self, mesh8):
        """A/B with the same seed and fresh batches each step: the int8
        trajectory must track exact pmean closely all along the curve —
        the per-block-scale quality gate for trusting the feature in real
        runs (VERDICT r1 item 9)."""
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)

        curves = {}
        for comp in (None, "int8"):
            rng = np.random.default_rng(7)
            state = init_state(model, opt, seed=1, mesh=mesh8)
            step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                                   donate=False, grad_compression=comp)
            ls = []
            for i in range(30):
                batch = put_global_batch(
                    mesh8, (rng.random((64, 784), np.float32),
                            np.eye(10, dtype=np.float32)[
                                rng.integers(0, 10, 64)]))
                state, m = step(state, batch, jax.random.key(i))
                ls.append(float(m["loss"]))
            curves[comp] = np.asarray(ls)
        delta = np.abs(curves["int8"] - curves[None])
        rel = delta / np.maximum(np.abs(curves[None]), 1e-3)
        # point-wise relative divergence stays small over the whole curve
        assert rel.max() < 0.02, (rel.max(), delta.max())

    def test_compression_requires_explicit_mode(self, mesh8):
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import make_train_step
        with pytest.raises(ValueError, match="explicit"):
            make_train_step(MnistMLP().loss, optim.sgd(0.1), mesh8,
                            mode="implicit", grad_compression="int8")

    def test_multi_data_axis_mesh_rejected(self):
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import make_train_step
        mesh = make_mesh("data=4,fsdp=2")
        with pytest.raises(ValueError, match="single data axis"):
            make_train_step(MnistMLP().loss, optim.sgd(0.1), mesh,
                            mode="explicit", grad_compression="int8")
