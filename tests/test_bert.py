"""Attention + BERT tests, incl. tensor-parallel sharding on the 2D mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dtf_tpu import optim
from dtf_tpu.models.bert import BertConfig, BertMLM
from dtf_tpu.nn.attention import MultiHeadAttention, causal_mask, dot_product_attention
from dtf_tpu.parallel import sharding as sh
from dtf_tpu.train.trainer import init_state, make_train_step, put_global_batch


class TestAttention:
    def test_softmax_attention_matches_naive(self):
        b, t, h, d = 2, 5, 2, 4
        k = jax.random.key(0)
        q, kk, v = (jax.random.normal(jax.random.key(i), (b, t, h, d))
                    for i in range(3))
        out = dot_product_attention(q, kk, v)
        # naive per-head loop
        for bi in range(b):
            for hi in range(h):
                logits = (q[bi, :, hi] @ kk[bi, :, hi].T) / np.sqrt(d)
                w = jax.nn.softmax(logits)
                np.testing.assert_allclose(np.asarray(out[bi, :, hi]),
                                           np.asarray(w @ v[bi, :, hi]),
                                           rtol=1e-5, atol=1e-6)

    def test_causal_mask_blocks_future(self):
        b, t, h, d = 1, 4, 1, 2
        q = jnp.ones((b, t, h, d))
        k = jnp.ones((b, t, h, d))
        v = jnp.arange(t, dtype=jnp.float32)[None, :, None, None] * jnp.ones((b, t, h, d))
        out = dot_product_attention(q, k, v, mask=causal_mask(t))
        # position 0 can only see position 0.
        np.testing.assert_allclose(np.asarray(out[0, 0, 0]), 0.0, atol=1e-6)

    def test_mha_shapes_and_axes(self):
        mha = MultiHeadAttention(dim=16, num_heads=4)
        p = mha.init(jax.random.key(0))
        y = mha.apply(p, jnp.ones((2, 7, 16)))
        assert y.shape == (2, 7, 16)
        assert mha.axes()["q"]["w"] == ("embed", "heads", "kv")


class TestBert:
    def test_forward_and_loss(self):
        cfg = BertConfig.tiny()
        m = BertMLM(cfg)
        p = m.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
        logits = m.apply(p, toks)
        assert logits.shape == (2, 16, cfg.vocab_size)
        loss, aux = m.loss(p, toks, rng=jax.random.key(2))
        assert bool(jnp.isfinite(loss))
        assert 0.05 < float(aux["masked_frac"]) < 0.3

    def test_masking_rates(self):
        cfg = BertConfig.tiny()
        m = BertMLM(cfg)
        toks = jnp.ones((64, 32), jnp.int32) * 7
        inputs, selected = m.mask_tokens(jax.random.key(0), toks)
        frac = float(jnp.mean(selected))
        assert frac == pytest.approx(0.15, abs=0.03)
        # ~80% of selected became [MASK]
        mask_frac = float(jnp.sum((inputs == cfg.mask_token) & selected)
                          / jnp.sum(selected))
        assert mask_frac == pytest.approx(0.8, abs=0.1)

    def test_fixed_k_masking_exact_count(self):
        cfg = BertConfig.tiny(mlm_predictions=4)
        m = BertMLM(cfg)
        toks = jnp.ones((16, 32), jnp.int32) * 7
        inputs, idx, targets = m.mask_tokens_fixed(jax.random.key(0), toks)
        assert idx.shape == (16, 4)
        # exactly K distinct positions per row
        for row in np.asarray(idx):
            assert len(set(row.tolist())) == 4
        np.testing.assert_array_equal(targets, np.full((16, 4), 7))
        # ~80% of the K selections became [MASK]
        sel_vals = jnp.take_along_axis(inputs, idx, axis=1)
        frac = float(jnp.mean(sel_vals == cfg.mask_token))
        assert frac == pytest.approx(0.8, abs=0.12)

    def test_masking_respects_pad_mask(self):
        """Padded positions are never selected for prediction, in both the
        dynamic and the fixed-K path (ADVICE r2: fixed-K previously chose
        over ALL T positions)."""
        cfg = BertConfig.tiny(mlm_predictions=4)
        m = BertMLM(cfg)
        toks = jnp.ones((16, 32), jnp.int32) * 7
        pad = jnp.arange(32)[None, :] < 10       # only 10 real positions
        pad = jnp.broadcast_to(pad, toks.shape)
        _, idx, _ = m.mask_tokens_fixed(jax.random.key(0), toks, pad)
        assert int(jnp.max(idx)) < 10
        _, selected = m.mask_tokens(jax.random.key(1), toks, pad)
        assert not bool(jnp.any(selected & ~pad))
        # and the loss path accepts a dict batch carrying the pad mask
        p = m.init(jax.random.key(2))
        loss, _ = m.loss(p, {"tokens": toks, "pad_mask": pad},
                         rng=jax.random.key(3))
        assert bool(jnp.isfinite(loss))

    def test_fixed_k_loss_trains(self):
        """K-position head: finite loss, gradients flow to every param
        (incl. the head), accounted FLOPs < dense FLOPs."""
        cfg = BertConfig.tiny(mlm_predictions=4)
        m = BertMLM(cfg)
        p = m.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0,
                                  cfg.vocab_size)
        (loss, aux), grads = jax.value_and_grad(
            lambda pp: m.loss(pp, toks, rng=jax.random.key(2)),
            has_aux=True)(p)
        assert bool(jnp.isfinite(loss))
        assert float(aux["masked_frac"]) == pytest.approx(4 / 32)
        gnorms = [float(jnp.abs(g).sum())
                  for g in jax.tree_util.tree_leaves(grads)]
        assert all(np.isfinite(gnorms))
        assert sum(1 for g in gnorms if g > 0) > len(gnorms) * 0.8
        dense = BertMLM(BertConfig.tiny())
        assert (m.train_flops_per_example(p)
                < dense.train_flops_per_example(p))

    def test_unrolled_layer_loop_matches_scan(self):
        """layer_loop='unroll' + remat_policy='attn' is the measured-fast
        path; loss and grads must equal the scanned default."""
        toks = jax.random.randint(jax.random.key(1), (2, 32), 0, 128)
        rng = jax.random.key(2)
        ms = BertMLM(BertConfig.tiny())
        mu = BertMLM(BertConfig.tiny(layer_loop="unroll", remat=True,
                                     remat_policy="attn"))
        p = ms.init(jax.random.key(0))
        (ls, _), gs = jax.value_and_grad(
            lambda q: ms.loss(q, toks, rng=rng), has_aux=True)(p)
        (lu, _), gu = jax.value_and_grad(
            lambda q: mu.loss(q, toks, rng=rng), has_aux=True)(p)
        assert float(ls) == pytest.approx(float(lu), rel=1e-6)
        for (path, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(gs),
                jax.tree_util.tree_leaves_with_path(gu)):
            np.testing.assert_allclose(a, b, atol=1e-5,
                                       err_msg=jax.tree_util.keystr(path))

    def test_param_axes_mirror_params(self):
        cfg = BertConfig.tiny()
        m = BertMLM(cfg)
        p = m.init(jax.random.key(0))
        ax = m.axes()
        pt = jax.tree_util.tree_structure(p)
        is_axes_leaf = lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x)
        at = jax.tree_util.tree_structure(
            jax.tree_util.tree_map(lambda x: 0, ax, is_leaf=is_axes_leaf))
        assert pt == at

    def test_tensor_parallel_shardings(self, mesh_2d):
        """Params sharded by rules on data=4,tensor=2: QKV on heads dim,
        MLP fc1 on out dim, embeddings on vocab."""
        cfg = BertConfig.tiny()
        m = BertMLM(cfg)
        shardings = sh.apply_rules(m.axes(), mesh_2d)
        assert shardings["layers"]["attn"]["q"]["w"].spec == P(None, None, "tensor", None)
        assert shardings["layers"]["fc1"]["w"].spec == P(None, None, "tensor")
        assert shardings["tok"]["table"].spec == P("tensor", None)

    def test_dp_tp_train_step(self, mesh_2d):
        """Full train step with params sharded TP + batch sharded DP."""
        cfg = BertConfig.tiny()
        m = BertMLM(cfg)
        opt = optim.adam(1e-3)
        shardings = sh.apply_rules(m.axes(), mesh_2d)
        state = init_state(m, opt, seed=0, mesh=mesh_2d,
                          param_shardings=shardings)
        step = make_train_step(m.loss, opt, mesh_2d, donate=False)
        toks = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, 16)).astype(np.int32)
        batch = put_global_batch(mesh_2d, toks)
        state2, metrics = step(state, batch, jax.random.key(0))
        assert bool(jnp.isfinite(metrics["loss"]))
        # params keep their TP sharding through the update
        assert state2["params"]["layers"]["fc1"]["w"].sharding.spec == P(None, None, "tensor")

    def test_loss_decreases(self):
        cfg = BertConfig.tiny()
        m = BertMLM(cfg)
        opt = optim.adam(3e-3)
        from dtf_tpu.parallel.mesh import make_mesh
        mesh = make_mesh("data=-1")
        state = init_state(m, opt, seed=0, mesh=mesh)
        step = make_train_step(m.loss, opt, mesh, donate=False)
        toks = np.random.default_rng(0).integers(0, 16, (32, 16)).astype(np.int32)
        batch = put_global_batch(mesh, toks)
        losses = []
        for i in range(30):
            state, metrics = step(state, batch, jax.random.key(i % 4))
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.8
