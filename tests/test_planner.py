"""Sharding planner (parallel/planner.py, ISSUE 19): candidate-ladder
feasibility under an HBM budget, loud infeasible rejection naming the
overflowing component, CostCard-vs-analytic agreement, --plan auto trainer
wiring (gauges, pinned-flag override, manifest round-trip + restore
attribution), and the activation-sharding fix for the SPMD partitioner's
involuntary-full-rematerialization warning (multichip dryrun legs)."""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu import telemetry as tel
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import ClusterConfig, TrainConfig
from dtf_tpu.models.bert import BertConfig, BertMLM
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.parallel import planner
from dtf_tpu.train.trainer import Trainer


GIB = 2.0**30


def tiny_bert():
    return BertMLM(BertConfig.tiny(num_layers=4, dim=64, mlp_dim=256,
                                   max_len=64))


class TestLadder:
    def test_ample_budget_picks_least_intrusive_rung(self, mesh8):
        # Wide (>=4-way) data axis: zero1 IS the least intrusive rung —
        # sharded update measured faster than dense's full-tree allreduce
        # and optimizer state is 1/N (planner._ZERO1_MIN_AXIS).
        p = planner.make_plan(MnistMLP(init_scale="fan_in"), mesh8,
                              batch_size=64, hbm_budget_bytes=4 * GIB,
                              optimizer=optim.adam(1e-3))
        assert p.grad_sync == "zero1" and not p.remat
        assert p.source == "analytic"
        assert 0 < p.predicted_hbm_bytes <= 4 * GIB
        # 8-way data axis: the ring wire wins (ISSUE 19 wire policy)
        assert p.grad_comm_dtype == "int8_ring"

    def test_narrow_mesh_keeps_dense_first(self, devices):
        from dtf_tpu.parallel.mesh import make_mesh
        mesh2 = make_mesh("data=2", devices=devices[:2])
        p = planner.make_plan(MnistMLP(init_scale="fan_in"), mesh2,
                              batch_size=64, hbm_budget_bytes=4 * GIB,
                              optimizer=optim.adam(1e-3))
        assert p.grad_sync == "dense" and not p.remat
        assert p.grad_comm_dtype == "int8"

    def test_tight_budget_climbs_ladder(self, devices):
        """A budget the dense rung overflows but zero1(+remat) fits:
        the plan lands on a zero1 rung with the SAME model (narrow mesh,
        where dense is still the first rung)."""
        from dtf_tpu.parallel.mesh import make_mesh
        mesh2 = make_mesh("data=2", devices=devices[:2])
        model = tiny_bert()
        ample = planner.make_plan(model, mesh2, batch_size=64,
                                  hbm_budget_bytes=4 * GIB,
                                  optimizer=optim.adam(1e-3))
        assert ample.grad_sync == "dense"
        dense_need = ample.predicted_hbm_bytes
        tight = planner.make_plan(model, mesh2, batch_size=64,
                                  hbm_budget_bytes=dense_need * 0.6,
                                  optimizer=optim.adam(1e-3))
        assert tight.grad_sync in ("zero1", "zero1_overlap")
        assert tight.predicted_hbm_bytes <= dense_need * 0.6
        assert tight.predicted_hbm_bytes < dense_need

    def test_wide_mesh_tight_budget_adds_remat(self, mesh8):
        """On a wide mesh the first rung is zero1/no-remat; a budget it
        overflows pushes the plan onto a remat rung."""
        model = tiny_bert()
        ample = planner.make_plan(model, mesh8, batch_size=64,
                                  hbm_budget_bytes=4 * GIB,
                                  optimizer=optim.adam(1e-3))
        assert ample.grad_sync == "zero1" and not ample.remat
        need = ample.predicted_hbm_bytes
        tight = planner.make_plan(model, mesh8, batch_size=64,
                                  hbm_budget_bytes=need * 0.9,
                                  optimizer=optim.adam(1e-3))
        assert tight.remat
        assert tight.predicted_hbm_bytes <= need * 0.9

    def test_infeasible_rejected_loudly_naming_component(self, mesh8):
        with pytest.raises(planner.PlanInfeasibleError) as ei:
            planner.make_plan(tiny_bert(), mesh8, batch_size=64,
                              hbm_budget_bytes=1e4,
                              optimizer=optim.adam(1e-3))
        err = ei.value
        # the exception carries AND prints the overflowing component
        names = [n for n, _ in planner._components(
            tiny_bert(), mesh8, batch_size=64, grad_sync="zero1_overlap",
            grad_bucket_mb=4.0, remat=True, remat_policy="full")]
        assert err.component in names
        assert err.component in str(err)
        assert f"{err.budget_bytes / GIB:.2f}" in str(err)

    def test_pinned_knobs_always_win(self, mesh8):
        p = planner.make_plan(
            MnistMLP(init_scale="fan_in"), mesh8, batch_size=64,
            hbm_budget_bytes=4 * GIB, optimizer=optim.adam(1e-3),
            pinned={"grad_sync": "zero1", "grad_comm_dtype": "bf16",
                    "grad_bucket_mb": 0.25})
        assert p.grad_sync == "zero1"
        assert p.grad_comm_dtype == "bf16"      # not auto-upgraded
        assert p.grad_bucket_mb == 0.25

    def test_wire_policy_by_axis_width(self):
        assert planner._wire_dtype(8, {}) == "int8_ring"
        assert planner._wire_dtype(4, {}) == "int8_ring"
        assert planner._wire_dtype(2, {}) == "int8"
        assert planner._wire_dtype(1, {}) is None

    def test_doc_round_trip(self, mesh8):
        p = planner.make_plan(MnistMLP(init_scale="fan_in"), mesh8,
                              batch_size=64, hbm_budget_bytes=4 * GIB)
        doc = json.loads(json.dumps(p.to_doc()))
        assert planner.ShardingPlan.from_doc(doc) == p


class TestCostCardBasis:
    def test_costcards_replace_analytic_on_known_geometry(self, mesh8,
                                                          tmp_path):
        """Capture a real train/step compile as a CostCard, then re-plan
        against the card library: source flips to 'costcards', the HBM
        prediction equals the measured compile-time peak, and the
        analytic estimate agrees within an order of magnitude (the
        closed-form model is a ranking device, not a simulator)."""
        from dtf_tpu.telemetry import costobs
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        model = MnistMLP(init_scale="fan_in")
        opt = optim.adam(1e-3)
        analytic = planner.make_plan(model, mesh8, batch_size=64,
                                     optimizer=opt,
                                     pinned={"grad_bucket_mb": 0.1})
        assert analytic.source == "analytic"

        state = init_state(model, opt, seed=1, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, mode="explicit",
                               donate=False)
        rng = np.random.default_rng(0)
        batch = put_global_batch(mesh8, (
            rng.random((64, 784)).astype(np.float32),
            np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]))
        # AOT-capture the compile exactly as the trainer's warmup does
        lowered = jax.jit(
            lambda s, b, k: step(s, b, k)).lower(
                state, batch, jax.random.key(0)).compile()
        costobs.get_observatory().reset()
        costobs.observe("train/step", ("aot", 64), lowered)
        costobs.get_observatory().write_jsonl(str(tmp_path))
        costobs.get_observatory().reset()

        measured = planner.make_plan(model, mesh8, batch_size=64,
                                     optimizer=opt, logdir=str(tmp_path),
                                     pinned={"grad_bucket_mb": 0.1})
        assert measured.source == "costcards"
        cards = costobs.read_costcards(str(tmp_path))
        card = [c for c in cards if c.site == "train/step"][0]
        assert measured.predicted_hbm_bytes == card.peak_hbm_bytes
        # order-of-magnitude agreement between the two sources
        ratio = analytic.predicted_hbm_bytes / measured.predicted_hbm_bytes
        assert 0.1 <= ratio <= 10.0, ratio

    def test_missing_cards_fall_back_to_analytic(self, mesh8, tmp_path):
        p = planner.make_plan(MnistMLP(init_scale="fan_in"), mesh8,
                              batch_size=64, logdir=str(tmp_path))
        assert p.source == "analytic"


class TestTrainerWiring:
    def _trainer(self, mesh, logdir, **cfg_kw):
        tel.reset()
        cfg = TrainConfig(batch_size=64, learning_rate=1e-3, epochs=1,
                          log_frequency=20, seed=1, logdir=str(logdir),
                          checkpoint_every=2, optimizer="adam",
                          **cfg_kw)
        return Trainer(Cluster(config=ClusterConfig(), mesh=mesh),
                       MnistMLP(init_scale="fan_in"),
                       optim.adam(1e-3), cfg)

    def test_plan_auto_sets_gauges_and_records_plan(self, mesh8,
                                                    tmp_path):
        t = self._trainer(mesh8, tmp_path, plan="auto")
        assert t._plan is not None
        # the plan's wire choice flowed into cfg and the explicit step
        assert t.cfg.grad_comm_dtype == "int8_ring"
        assert t.mode == "explicit"
        snap = tel.get_registry().snapshot()
        assert snap["plan/active"]["value"] == 1
        assert snap["plan/source_idx"]["value"] == \
            planner.PLAN_SOURCES.index(t._plan.source)
        assert snap["plan/predicted_hbm_bytes"]["value"] > 0
        assert snap["plan/hbm_budget_bytes"]["value"] > 0
        # recorded for the report --explain audit
        on_disk = planner.read_plan(str(tmp_path))
        assert on_disk == t._plan
        assert planner.audit_lines(str(tmp_path))
        t.ckpt.close()

    def test_unplanned_run_books_no_plan_gauges(self, mesh8, tmp_path):
        t = self._trainer(mesh8, tmp_path)
        assert t._plan is None
        assert "plan/active" not in tel.get_registry().snapshot()
        assert planner.read_plan(str(tmp_path)) is None
        assert planner.audit_lines(str(tmp_path)) == []
        t.ckpt.close()

    def test_pinned_flags_override_plan_auto(self, mesh8, tmp_path):
        """Hand-pinned CLI knobs survive --plan auto verbatim."""
        t = self._trainer(mesh8, tmp_path, plan="auto",
                          grad_sync="zero1", grad_comm_dtype="bf16",
                          grad_bucket_mb=0.1)
        assert t.cfg.grad_sync == "zero1"
        assert t.cfg.grad_comm_dtype == "bf16"
        assert t.cfg.grad_bucket_mb == 0.1
        assert t._plan.grad_sync == "zero1"
        t.ckpt.close()

    def test_infeasible_budget_raises_before_compile(self, mesh8,
                                                     tmp_path):
        with pytest.raises(planner.PlanInfeasibleError, match="HBM"):
            self._trainer(mesh8, tmp_path, plan="auto",
                          plan_hbm_gb=1e-6)

    # checkpoint round-trip integration (~3s of save/restore compiles):
    # full-suite coverage, not tier-1's 'not slow' budget
    @pytest.mark.slow
    def test_plan_round_trips_manifest_and_restore_logs_change(
            self, mesh8, tmp_path, caplog):
        """The manifest records the plan; a resume WITHOUT --plan auto
        logs the plan-change attribution line (restore_robust)."""
        import logging

        from dtf_tpu.data import load_mnist

        t = self._trainer(mesh8, tmp_path / "run", plan="auto")
        t.fit(load_mnist(seed=1), epochs=1, max_steps=2)
        t.ckpt.close()
        meta = t.ckpt.manifest_meta(t.ckpt.latest_step())
        assert meta["run"]["plan"] == t._plan.summary()
        assert meta["run"]["grad_comm_dtype"] == "int8_ring"

        tel.reset()
        cfg = TrainConfig(batch_size=64, learning_rate=1e-3, epochs=1,
                          log_frequency=20, seed=1,
                          logdir=str(tmp_path / "run"),
                          checkpoint_every=2, resume=True,
                          optimizer="adam")
        with caplog.at_level(logging.WARNING, logger="dtf_tpu"):
            t2 = Trainer(Cluster(config=ClusterConfig(), mesh=mesh8),
                         MnistMLP(init_scale="fan_in"),
                         optim.adam(1e-3), cfg)
        assert any("plan restore" in r.message
                   and "(manual)" in r.message
                   for r in caplog.records)
        t2.ckpt.close()

    @pytest.mark.slow
    def test_manifest_unplanned_runs_unchanged(self, mesh8, tmp_path):
        """No plan key on manual runs: the pinned exact-dict manifest
        contract from the grad_sync tests still holds."""
        from dtf_tpu.data import load_mnist

        t = self._trainer(mesh8, tmp_path, grad_sync="zero1",
                          grad_bucket_mb=0.1)
        t.fit(load_mnist(seed=1), epochs=1, max_steps=2)
        t.ckpt.close()
        meta = t.ckpt.manifest_meta(t.ckpt.latest_step())
        assert "plan" not in meta["run"]


_REMAT_PROBE = r"""
import numpy as np, jax
jax.config.update("jax_platforms", "cpu")
from dtf_tpu import optim
from dtf_tpu.models.bert import BertConfig, BertMLM
from dtf_tpu.parallel import sharding as sh
from dtf_tpu.parallel.mesh import make_mesh
from dtf_tpu.train.trainer import init_state, make_train_step, put_global_batch
from jax.sharding import NamedSharding, PartitionSpec as P

mesh = make_mesh("data=2,fsdp=2,tensor=2")
act = NamedSharding(mesh, P(("data", "fsdp"), None, "tensor"))
for tag, sharding in (("constrained", act), ("unconstrained", None)):
    cfg = BertConfig.tiny(num_heads=4, dim=32, mlp_dim=64,
                          act_sharding=sharding)
    model = BertMLM(cfg)
    shardings = sh.apply_rules(model.axes(), mesh, sh.fsdp_rules())
    opt = optim.adam(1e-3)
    state = init_state(model, opt, seed=0, mesh=mesh,
                       param_shardings=shardings)
    step = make_train_step(model.loss, opt, mesh, mode="implicit")
    toks = np.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (16, cfg.max_len)), dtype=np.int32)
    state, metrics = step(state, put_global_batch(mesh, toks),
                          jax.random.key(0))
    print(f"MARK {tag} loss={float(metrics['loss']):.6f}")
"""


class TestActivationShardingSuppression:
    # ~15s: a fresh-subprocess 8-device dryrun compile; rides the
    # full-suite run rather than tier-1's 'not slow' budget.
    @pytest.mark.slow
    def test_dryrun_mesh_has_no_involuntary_remat_warning(self):
        """ISSUE 19 satellite: under the planner's activation policy the
        SPMD partitioner compiles the multichip-dryrun DP/FSDP/TP step
        WITHOUT 'Involuntary full rematerialization'; the unconstrained
        control on the same mesh still trips it (so the assertion can't
        rot silently if XLA stops printing the warning)."""
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8")
        r = subprocess.run([sys.executable, "-c", _REMAT_PROBE],
                           capture_output=True, text=True, env=env,
                           timeout=500)
        assert r.returncode == 0, r.stderr[-2000:]
        out = r.stdout + r.stderr
        marks = [l for l in out.splitlines() if l.startswith("MARK")]
        assert len(marks) == 2, marks
        constrained_end = out.index("MARK constrained")
        head = out[:constrained_end]
        tail = out[constrained_end:]
        assert "Involuntary full rematerialization" not in head, head
        assert "Involuntary full rematerialization" in tail
        # the constraint is layout-only: losses agree to fp noise
        losses = [float(m.split("loss=")[1]) for m in marks]
        assert abs(losses[0] - losses[1]) < 1e-4
