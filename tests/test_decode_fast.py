"""The fast decode data path (ISSUE 14): batched multi-request
prefill, block-indexed narrowed paged decode, speculative decoding.

The three ISSUE-level pins:

* **coalescing determinism** — the same trace produces the same batch
  log and bitwise-identical tokens whether prefill ran solo or
  coalesced (and coalescing demonstrably cuts prefill dispatches);
* **narrowed-geometry parity** — narrowed decode (live-context table
  buckets + hot pool prefix) emits tokens identical to the full-window
  whole-pool baseline AND to the contiguous ``GPT.generate`` oracle,
  greedy and sampled, with the compiled-geometry count pinned;
* **speculative token identity** — the spec engine's greedy stream is
  bitwise the sequential engine's on the same trace (the verify step
  emits the model's own choices; drafts only move the acceptance
  rate), while acceptance > 0 proves speculation actually engaged.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.serve import (BlockAllocator, KVPool, ServingEngine,
                           VirtualClock, blocks_for)
from dtf_tpu.serve import decode as dec
from dtf_tpu.serve.engine import _pow2_bucket
from dtf_tpu.serve.spec import propose_drafts

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    from dtf_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig.tiny())
    return model, model.init(jax.random.key(0))


def _mk_engine(model, params, **kw):
    kw.setdefault("clock", VirtualClock())
    kw.setdefault("num_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("blocks_per_slot", 8)
    kw.setdefault("num_blocks", 1 + 3 * 8)
    return ServingEngine(model, params, **kw)


def _mk_trace(rng, n, *, qps=50.0, p_lens=(3, 5, 8, 12),
              o_lens=(3, 6, 10), temperature=0.0, vocab=128):
    trace, t = [], 0.0
    for rid in range(n):
        t += float(rng.exponential(1.0)) / qps
        p = int(rng.choice(p_lens))
        trace.append((t, {
            "rid": rid,
            "prompt": rng.integers(0, vocab, (p,)).astype(np.int32),
            "max_new_tokens": int(rng.choice(o_lens)),
            "temperature": temperature,
        }))
    return trace


def _completed_tokens(results):
    return {r.rid: list(r.tokens) for r in results.values()
            if r.status == "completed"}


# ---------------------------------------------------------------------------
# buckets / allocator / pool plumbing (no jax compilation)
# ---------------------------------------------------------------------------


class TestBuckets:
    def test_pow2_bucket(self):
        assert [_pow2_bucket(n, 64) for n in (1, 2, 3, 5, 8, 9)] == \
            [1, 2, 4, 8, 8, 16]
        assert _pow2_bucket(100, 48) == 48          # cap clamps
        assert _pow2_bucket(0, 8) == 1              # floor at 1

    def test_highest_used_tracks_incrementally(self):
        a = BlockAllocator(64)
        assert a.highest_used() == 0
        got = a.allocate(3)                         # [1, 2, 3]
        assert a.highest_used() == 3
        more = a.allocate(2)                        # [4, 5]
        assert a.highest_used() == 5
        a.free(more)
        assert a.highest_used() == 3
        a.free(got)
        assert a.highest_used() == 0
        # fragmented reuse: high-water follows the max live id exactly
        a.allocate(1)
        b2 = a.allocate(4)
        a.free(b2[:3])
        assert a.highest_used() == b2[3]


class TestKVPoolHot:
    def _cfg(self):
        from dtf_tpu.models.gpt import GPTConfig
        return GPTConfig.tiny()

    def test_ensure_hot_roundtrip_preserves_rows(self):
        pool = KVPool.create(self._cfg(), 16, 4)
        assert pool.hot_blocks == 16 and pool.num_blocks == 16
        marked = pool.k.at[:, 9].set(7.0)
        pool.k = marked
        pool.ensure_hot(4)
        assert pool.hot_blocks == 4
        assert pool.num_blocks == 16                # nothing lost
        pool.ensure_hot(16)
        assert pool.hot_blocks == 16
        # block 9's rows came back from cold storage intact
        np.testing.assert_array_equal(np.asarray(pool.k[:, 9]),
                                      np.asarray(marked[:, 9]))

    def test_ensure_hot_bounds(self):
        pool = KVPool.create(self._cfg(), 8, 4)
        with pytest.raises(ValueError, match="hot prefix"):
            pool.ensure_hot(0)
        with pytest.raises(ValueError, match="hot prefix"):
            pool.ensure_hot(9)

    def test_external_pool_geometry_validated(self, tiny_model):
        model, params = tiny_model
        pool = KVPool.create(self._cfg(), 16, 4)
        with pytest.raises(ValueError, match="pool geometry"):
            ServingEngine(model, params, num_slots=2, block_size=4,
                          blocks_per_slot=4, num_blocks=32, pool=pool)


# ---------------------------------------------------------------------------
# batched prefill coalescing (ISSUE pin)
# ---------------------------------------------------------------------------


class TestPrefillCoalescing:
    def _burst(self, n=3, p_len=5, max_new=5, temperature=0.0):
        return [(0.0, dict(rid=i,
                           prompt=np.arange(i, i + p_len,
                                            dtype=np.int32) % 128,
                           max_new_tokens=max_new,
                           temperature=temperature)) for i in range(n)]

    @pytest.mark.parametrize("temperature", [0.0, 1.0])
    def test_solo_vs_coalesced_bitwise(self, tiny_model, temperature):
        """THE determinism pin: same trace => same batch log and
        bitwise-identical tokens whether prefill ran solo or coalesced
        — and the coalesced engine dispatched ONE prefill call for the
        same-bucket burst the solo engine dispatched three for."""
        model, params = tiny_model
        trace = self._burst(temperature=temperature)

        def run(coalesce):
            eng = _mk_engine(model, params, seed=42,
                             coalesce_prefill=coalesce)
            res = eng.run([(t, dict(kw)) for t, kw in trace])
            return eng, _completed_tokens(res)

        e_co, t_co = run(True)
        e_solo, t_solo = run(False)
        assert t_co == t_solo and len(t_co) == 3
        assert e_co.batch_log == e_solo.batch_log
        assert e_co.prefill_calls == 1
        assert e_solo.prefill_calls == 3

    def test_mixed_buckets_group_by_padded_len(self, tiny_model):
        """Admissions of different prompt buckets in one iteration run
        as separate calls, in admission order (the scheduler's
        decisions are untouched by dispatch grouping)."""
        model, params = tiny_model
        trace = [(0.0, dict(rid=0, prompt=np.arange(3, dtype=np.int32),
                            max_new_tokens=3)),
                 (0.0, dict(rid=1, prompt=np.arange(3, dtype=np.int32),
                            max_new_tokens=3)),
                 (0.0, dict(rid=2, prompt=np.arange(7, dtype=np.int32),
                            max_new_tokens=3))]
        eng = _mk_engine(model, params, prefill_token_budget=64)
        eng.run(trace)
        # rid 0+1 share the 4-row bucket (one call), rid 2 pads to 8
        assert eng.prefill_calls == 2
        prefills = [e[1] for e in eng.batch_log if e[0] == "prefill"]
        assert prefills == [0, 1, 2]

    def test_batch_size_histogram_observed(self, tiny_model):
        import dtf_tpu.telemetry as tel
        model, params = tiny_model
        tel.reset()
        eng = _mk_engine(model, params)
        eng.run(self._burst())
        h = tel.histogram("serve/prefill_batch_size")
        assert h.count == 1 and h.total == 3
        assert eng.summary()["prefill_calls"] == 1


# ---------------------------------------------------------------------------
# narrowed decode geometry (ISSUE pin)
# ---------------------------------------------------------------------------


class TestNarrowedDecode:
    def test_narrow_matches_baseline_and_generate(self, tiny_model):
        """Narrowed geometry (table buckets + hot prefix) vs the
        full-window whole-pool baseline vs the contiguous
        ``GPT.generate`` oracle: one token stream, three data paths."""
        model, params = tiny_model
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 128, (n,)).astype(np.int32)
                   for n in (5, 8, 3, 12)]
        new = [10, 6, 12, 7]
        refs = []
        for p, n in zip(prompts, new):
            out = model.generate(params, jnp.asarray(p)[None], n,
                                 temperature=0.0)
            refs.append(np.asarray(out)[0, len(p):].tolist())
        trace = [(0.01 * i, dict(rid=i, prompt=p, max_new_tokens=n))
                 for i, (p, n) in enumerate(zip(prompts, new))]
        for narrow in (True, False):
            eng = _mk_engine(model, params, num_blocks=1 + 3 * 6,
                             blocks_per_slot=6, narrow_decode=narrow)
            res = eng.run(list(trace))
            for i in range(4):
                assert res[i].tokens == refs[i], \
                    f"narrow={narrow} request {i} diverged"

    @pytest.mark.parametrize("temperature", [0.0, 1.0])
    def test_oversized_pool_token_identity(self, tiny_model, temperature):
        """An 8x oversized pool must not change a single token, and the
        narrowed engine must never heat more than the live prefix."""
        model, params = tiny_model
        trace = _mk_trace(np.random.default_rng(9), 6,
                          temperature=temperature)

        def run(num_blocks, narrow=True):
            eng = _mk_engine(model, params, seed=11,
                             num_blocks=num_blocks, narrow_decode=narrow)
            res = eng.run([(t, dict(kw)) for t, kw in trace])
            return eng, _completed_tokens(res)

        e_tight, t_tight = run(1 + 3 * 8)
        e_over, t_over = run(200)
        assert t_tight == t_over and len(t_over) == 6
        assert e_over.pool.hot_blocks < 200
        assert e_over.pool.num_blocks == 200

    def test_geometry_bucket_count_pinned(self, tiny_model):
        """Recompile discipline: geometries are power-of-two buckets,
        so a whole trace compiles O(log) decode shapes — and a second
        engine over the same model adds ZERO new compiled steps."""
        model, params = tiny_model
        trace = _mk_trace(np.random.default_rng(21), 8)

        def run():
            eng = _mk_engine(model, params, seed=5)
            eng.run([(t, dict(kw)) for t, kw in trace])
            return eng

        run()
        cache_after_first = set(model._serve_fn_cache)
        eng = run()
        assert set(model._serve_fn_cache) == cache_after_first
        decode_geoms = {k for k in eng._compiled if k[0] == "decode"}
        # window is 8 blocks -> at most 1,2,4,8 table buckets
        assert 1 <= len(decode_geoms) <= 4
        for key in decode_geoms:
            nb = key[1]
            assert nb == _pow2_bucket(nb, 8)


# ---------------------------------------------------------------------------
# speculative decoding (ISSUE pin)
# ---------------------------------------------------------------------------


class TestDrafter:
    def test_longest_most_recent_match_wins(self):
        #          0  1  2  3  4  5  6  7
        ctx = [5, 6, 7, 9, 5, 6, 7, 9]  # suffix (6,7,9) seen at 1..3
        assert propose_drafts(ctx + [5, 6, 7], 2) == [9, 5]
        # most RECENT occurrence preferred: continuation after the
        # second (5,6) run is (7,9), same here, but pin recency with an
        # asymmetric context
        ctx2 = [1, 2, 3, 9, 9, 1, 2, 4]
        assert propose_drafts(ctx2 + [1, 2], 1) == [4]

    def test_no_match_returns_empty(self):
        assert propose_drafts([1, 2, 3, 4], 3) == []
        assert propose_drafts([7], 3) == []
        assert propose_drafts([1, 2, 1, 2], 0) == []

    def test_k_clamps_to_available_continuation(self):
        ctx = [3, 4, 5, 3, 4]
        assert propose_drafts(ctx, 4) == [5, 3, 4]


class TestSpeculative:
    def test_greedy_token_identity_vs_sequential(self, tiny_model):
        """THE spec pin: same trace, spec_k=4 vs spec_k=0 — bitwise
        identical completed token streams, same completion statuses,
        and drafts were actually proposed AND accepted (the win is
        attributable, not vacuous)."""
        model, params = tiny_model
        trace = _mk_trace(np.random.default_rng(7), 8, qps=30.0,
                          o_lens=(6, 10, 16))

        def run(k):
            eng = _mk_engine(model, params, seed=1, spec_k=k)
            res = eng.run([(t, dict(kw)) for t, kw in trace])
            stat = {r.rid: r.status for r in res.values()}
            return eng, _completed_tokens(res), stat

        e_spec, t_spec, s_spec = run(4)
        e_base, t_base, s_base = run(0)
        assert t_spec == t_base and s_spec == s_base
        assert e_spec.spec_proposed > 0
        assert e_spec.spec_accepted > 0
        assert e_spec.spec_accepted <= e_spec.spec_proposed
        # fewer decode dispatches for the same tokens is the point
        assert e_spec.iterations <= e_base.iterations

    def test_sampled_token_identity_vs_sequential(self, tiny_model):
        """Sampled streams hold too: the verify step draws position s
        with the request's (seed, rid, count+s) key — exactly the
        sequential stream's draw."""
        model, params = tiny_model
        trace = _mk_trace(np.random.default_rng(13), 6, temperature=1.0)

        def run(k):
            eng = _mk_engine(model, params, seed=2, spec_k=k)
            return _completed_tokens(eng.run(
                [(t, dict(kw)) for t, kw in trace]))

        assert run(4) == run(0)

    def test_eos_mid_window_stops_exactly(self, tiny_model):
        """EOS accepted mid-verify-window must finish the request at
        the EOS token, exactly like the sequential engine."""
        model, params = tiny_model
        rng = np.random.default_rng(29)
        prompt = rng.integers(0, 128, (6,)).astype(np.int32)
        ref = np.asarray(model.generate(
            params, jnp.asarray(prompt)[None], 10,
            temperature=0.0))[0, 6:].tolist()
        eos = ref[2]
        eng = _mk_engine(model, params, spec_k=4)
        res = eng.run([(0.0, dict(rid=0, prompt=prompt,
                                  max_new_tokens=10, eos_id=eos))])
        assert res[0].tokens == ref[:3]
        assert eng.scheduler.allocator.used_blocks == 0

    def test_summary_and_instruments(self, tiny_model):
        import dtf_tpu.telemetry as tel
        model, params = tiny_model
        tel.reset()
        eng = _mk_engine(model, params, spec_k=3)
        eng.run(_mk_trace(np.random.default_rng(31), 5, o_lens=(8, 12)))
        s = eng.summary()
        assert s["spec_k"] == 3
        assert s["spec_proposed"] == eng.spec_proposed > 0
        assert s["spec_accepted"] == eng.spec_accepted
        assert s["spec_acceptance"] == pytest.approx(
            eng.spec_accepted / eng.spec_proposed)
        assert tel.counter("serve/spec_proposed_total").value == \
            eng.spec_proposed
        assert tel.counter("serve/spec_accepted_total").value == \
            eng.spec_accepted

    def test_verify_fn_single_token_matches_decode_fn(self, tiny_model):
        """Fn-level: a verify window with n_in=1 is the plain decode
        step — same next token, same health flag."""
        model, params = tiny_model
        from dtf_tpu.serve.paged_kv import KVPool
        pool = KVPool.create(model.cfg, 9, 4)
        rng = np.random.default_rng(0)
        pk = jnp.asarray(rng.normal(size=pool.k.shape).astype(np.float32))
        pv = jnp.asarray(rng.normal(size=pool.v.shape).astype(np.float32))
        table = jnp.asarray(np.array([[3, 1, -1, -1], [2, 5, 7, -1]],
                                     np.int32))
        tok = np.array([5, 9], np.int32)
        pos = jnp.asarray(np.array([6, 9], np.int32))
        temps = jnp.asarray(np.zeros(2, np.float32))
        seeds = jnp.asarray(np.array([1, 2], np.uint32))
        counts = jnp.asarray(np.array([3, 4], np.int32))
        fd = dec.build_decode_fn(model, num_slots=2, blocks_per_slot=4,
                                 block_size=4)
        fv = dec.build_verify_fn(model, num_slots=2, blocks_per_slot=4,
                                 block_size=4, width=3)
        nxt, ok, _, _ = fd(params, pk, pv, table, jnp.asarray(tok), pos,
                           temps, seeds, counts)
        toks_w = np.zeros((2, 3), np.int32)
        toks_w[:, 0] = tok
        out, okv, _, _ = fv(params, pk, pv, table, jnp.asarray(toks_w),
                            pos, jnp.asarray(np.ones(2, np.int32)),
                            temps, seeds, counts)
        np.testing.assert_array_equal(np.asarray(nxt),
                                      np.asarray(out)[:, 0])
        np.testing.assert_array_equal(np.asarray(ok), np.asarray(okv))

    def test_scheduler_learns_tokens_per_slot(self):
        from dtf_tpu.serve.scheduler import Scheduler
        s = Scheduler(num_slots=2, allocator=BlockAllocator(16),
                      block_size=4, blocks_per_slot=4)
        s.observe_decode(0.010)
        assert s.decode_iter_s == pytest.approx(0.010)
        # a verify that emitted 2 tokens/slot halves the per-token rate
        s2 = Scheduler(num_slots=2, allocator=BlockAllocator(16),
                      block_size=4, blocks_per_slot=4)
        s2.observe_decode(0.010, tokens_per_slot=2.0)
        assert s2.decode_iter_s == pytest.approx(0.005)

    def test_verify_charge_kind(self):
        clock = VirtualClock()
        clock.charge("verify", batch=3, tokens=8)
        expect = (8.0 + 0.5 * 3 + clock.verify_per_token_ms * 8) / 1e3
        assert clock.now() == pytest.approx(expect)


# ---------------------------------------------------------------------------
# paged-attention Pallas kernel (interpret-mode parity)
# ---------------------------------------------------------------------------


class TestPagedAttentionKernel:
    @pytest.mark.parametrize("llama", [False, True])
    def test_kernel_decode_matches_xla_gather(self, llama):
        """The TPU-build data path: build_decode_fn(kernel=True) runs
        the block-indexed Pallas kernel (interpret mode on CPU) and
        must emit the same greedy tokens as the XLA gather oracle on a
        fragmented table — including GQA + RoPE wiring."""
        from dtf_tpu.models.gpt import GPT, GPTConfig
        cfg = (GPTConfig.tiny(num_kv_heads=2, rope=True) if llama
               else GPTConfig.tiny())
        model = GPT(cfg)
        params = model.init(jax.random.key(1))
        from dtf_tpu.serve.paged_kv import KVPool
        pool = KVPool.create(cfg, 9, 4)
        rng = np.random.default_rng(2)
        pk = jnp.asarray(rng.normal(size=pool.k.shape).astype(np.float32))
        pv = jnp.asarray(rng.normal(size=pool.v.shape).astype(np.float32))
        table = jnp.asarray(np.array([[3, 1, -1, -1], [2, 5, 7, -1]],
                                     np.int32))
        args = (params, pk, pv, table,
                jnp.asarray(np.array([5, 9], np.int32)),
                jnp.asarray(np.array([6, 9], np.int32)),
                jnp.asarray(np.zeros(2, np.float32)),
                jnp.asarray(np.array([1, 2], np.uint32)),
                jnp.asarray(np.array([3, 4], np.int32)))
        fx = dec.build_decode_fn(model, num_slots=2, blocks_per_slot=4,
                                 block_size=4)
        fk = dec.build_decode_fn(model, num_slots=2, blocks_per_slot=4,
                                 block_size=4, kernel=True)
        nx, okx, kx, vx = fx(*args)
        nk, okk, kk, vk = fk(*args)
        np.testing.assert_array_equal(np.asarray(nx), np.asarray(nk))
        np.testing.assert_array_equal(np.asarray(okx), np.asarray(okk))
        np.testing.assert_allclose(np.asarray(kx), np.asarray(kk),
                                   rtol=2e-5, atol=2e-5)

    def test_kernel_standalone_matches_reference(self):
        """paged_attention against a dense numpy softmax reference on a
        known table/pos layout."""
        from dtf_tpu.ops.decode_kernel import paged_attention
        rng = np.random.default_rng(0)
        b, nh, kvh, hd, bs, nb, npool = 2, 4, 4, 8, 4, 3, 8
        hn, kn = nh * hd, kvh * hd
        q = rng.normal(size=(b, hn)).astype(np.float32)
        ks = rng.normal(size=(b, kn)).astype(np.float32)
        vs = rng.normal(size=(b, kn)).astype(np.float32)
        pool_k = rng.normal(size=(npool, bs, kn)).astype(np.float32)
        pool_v = rng.normal(size=(npool, bs, kn)).astype(np.float32)
        table = np.array([[2, 4, 0], [1, 3, 5]], np.int32)
        pos = np.array([5, 9], np.int32)
        out = np.asarray(paged_attention(
            jnp.asarray(q), jnp.asarray(ks), jnp.asarray(vs),
            jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(table), jnp.asarray(pos),
            num_heads=nh, kv_heads=kvh))
        for bi in range(b):
            kc = pool_k[table[bi]].reshape(-1, kvh, hd)
            vc = pool_v[table[bi]].reshape(-1, kvh, hd)
            kc = np.concatenate([kc[:pos[bi]],
                                 ks[bi].reshape(1, kvh, hd)])
            vc = np.concatenate([vc[:pos[bi]],
                                 vs[bi].reshape(1, kvh, hd)])
            qh = q[bi].reshape(nh, hd)
            for h in range(nh):
                s = kc[:, h] @ qh[h] * hd ** -0.5
                w = np.exp(s - s.max())
                w /= w.sum()
                ref = w @ vc[:, h]
                np.testing.assert_allclose(
                    out[bi].reshape(nh, hd)[h], ref, rtol=1e-5,
                    atol=1e-5)


# ---------------------------------------------------------------------------
# bench satellites: ladder engine mode, spec A/B, ledger decode rows,
# check_gates max_tpot_p99_ms
# ---------------------------------------------------------------------------


class TestLadderEngineModes:
    def test_paged_mode_reports_fit(self):
        from dtf_tpu.bench.decode_ladder import run_engine
        r = run_engine("tiny", "paged", streams=2, ladder=(3, 6),
                       reps=1, prompt_len=4, block_size=4)
        assert r["rig"] == "decode_tiny_paged_s2_bs4"
        assert r["narrow"] is True and r["spec_k"] == 0
        assert len(r["ladder"]) == 2
        assert "per_token_us" in r

    def test_spec_mode_reports_acceptance(self):
        from dtf_tpu.bench.decode_ladder import run_engine
        r = run_engine("tiny", "spec", streams=2, ladder=(4, 8),
                       reps=1, prompt_len=4, block_size=4, spec_k=3)
        assert r["rig"] == "decode_tiny_spec_s2_bs4_k3"
        assert r["spec_k"] == 3
        assert r["spec_proposed"] >= 0
        assert "spec_acceptance" in r

    def test_oversized_pool_must_cover_tight(self):
        from dtf_tpu.bench.decode_ladder import run_engine
        with pytest.raises(ValueError, match="pool_blocks"):
            run_engine("tiny", "paged", streams=2, ladder=(3, 6),
                       reps=1, prompt_len=4, block_size=4, pool_blocks=3)


class TestSpecLoadAB:
    def test_spec_ab_gates_green_on_pinned_trace(self, tiny_model):
        """The CI gate in-process: the pinned decode-fast-lane trace
        must pass token identity + strict p99 TPOT improvement +
        acceptance, and fail an absurd absolute ceiling
        (falsifiability)."""
        import argparse
        from dtf_tpu.bench.serve_load import spec_ab
        model, params = tiny_model

        def ns_for(ceiling):
            return argparse.Namespace(
                qps_list=[10.0], requests=32, seed=5,
                prompt_lens_list=[4, 8, 16],
                output_lens_list=[16, 32, 48], temperature=0.0,
                top_k=0, top_p=1.0, slots=4, block_size=16,
                pool_blocks=None, max_queue=256, slo_ttft_ms=400.0,
                clock="virtual", spec_k=4, trace_vocab=None,
                max_tpot_p99_ms=ceiling, logdir=None)

        r = spec_ab(model, params, ns_for(0.0))
        assert r["ok"], r["gates"]
        assert r["token_identity"]
        assert r["spec"]["tpot_ms_p99"] < r["no_spec"]["tpot_ms_p99"]
        r_absurd = spec_ab(model, params, ns_for(0.001))
        assert not r_absurd["ok"]
        assert any("max_tpot_p99_ms" in ln and "FAIL" in ln
                   for ln in r_absurd["gates"])


class TestLedgerDecodeRows:
    def _ledger_mod(self):
        import importlib
        import os
        import sys
        scripts = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts")
        if scripts not in sys.path:
            sys.path.insert(0, scripts)
        return importlib.import_module("bench_ledger")

    def _decode_rows(self, *vals, rig="decode_tiny_paged"):
        rows = []
        for i, v in enumerate(vals, start=1):
            rows.append({"run": f"DECODE_r{i:02d}", "kind": "decode",
                         "n": i, "commit": None, "rig": rig,
                         "tok_s_aggregate": v, "per_token_us": None,
                         "spec_acceptance": None, "ok": v is not None,
                         "error": None if v is not None else "no_tok_s",
                         "stage": None if v is not None else "ladder_fit"})
        return rows

    def test_decode_round_file_folds(self, tmp_path):
        bl = self._ledger_mod()
        doc = {"rig": "decode_tiny_paged", "preset": "tiny",
               "mode": "paged", "tok_s_aggregate": 3500.0,
               "per_token_us": 857.0}
        p = tmp_path / "DECODE_r01.json"
        p.write_text(json.dumps(doc))
        row = bl.decode_row(str(p), str(tmp_path))
        assert row["kind"] == "decode" and row["n"] == 1
        assert row["ok"] and row["tok_s_aggregate"] == 3500.0
        # a no-signal ladder folds as an errored round, not a gap
        doc["warning"] = "non-positive slope"
        p2 = tmp_path / "DECODE_r02.json"
        p2.write_text(json.dumps(doc))
        row2 = bl.decode_row(str(p2), str(tmp_path))
        assert not row2["ok"] and row2["error"]

    def test_decode_gate_green_and_regression(self):
        bl = self._ledger_mod()
        ok, lines = bl.check_ledger(self._decode_rows(3500.0, 3400.0))
        assert ok, lines
        ok, lines = bl.check_ledger(self._decode_rows(3500.0, 2000.0))
        assert not ok
        assert any("REGRESSION" in ln and "decode_tiny_paged" in ln
                   for ln in lines)

    def test_committed_decode_round_is_green(self):
        import os
        bl = self._ledger_mod()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rows = bl.read_ledger(os.path.join(repo, "LEDGER.jsonl"))
        dec_rows = [r for r in rows if r["kind"] == "decode"]
        assert dec_rows, "no committed decode rows in LEDGER.jsonl"
        assert all(r["ok"] for r in dec_rows)


class TestCheckGatesTpot:
    def test_tpot_ceiling_green_fail_absent(self):
        from dtf_tpu.telemetry.report import check_gates
        rep = {"telemetry": {"serving": {"tpot_ms_p99": 9.5}}}
        ok, lines = check_gates(rep, max_tpot_p99_ms=10.0)
        assert ok, lines
        ok, _ = check_gates(rep, max_tpot_p99_ms=9.0)
        assert not ok
        # absence of evidence fails the gate, it does not pass it
        ok, lines = check_gates({"telemetry": {"serving": {}}},
                                max_tpot_p99_ms=10.0)
        assert not ok
        assert any("not measured" in ln for ln in lines)
