"""Trainer integration tests on the simulated 8-device mesh (SURVEY.md §4:
end-to-end MNIST convergence; implicit/explicit step equivalence;
determinism; log-format golden contract)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import ClusterConfig, TrainConfig
from dtf_tpu.data import load_mnist
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.parallel.mesh import make_mesh
from dtf_tpu.train.metrics import format_step_line
from dtf_tpu.train.trainer import (
    Trainer, init_state, make_train_step, put_global_batch,
)


def make_cluster(mesh):
    return Cluster(config=ClusterConfig(), mesh=mesh)


@pytest.fixture()
def small_cfg(tmp_path):
    return TrainConfig(batch_size=64, learning_rate=0.05, epochs=1,
                       log_frequency=50, seed=1, logdir=str(tmp_path))


class TestTrainStep:
    def test_explicit_mode_rejects_model_axes(self, mesh_2d):
        """Explicit (shard_map) mode keeps params replicated, so a mesh
        with model axes must fail loudly instead of silently degrading to
        replicated compute (README: 'Implicit vs explicit mode')."""
        model = MnistMLP()
        with pytest.raises(ValueError, match="data-parallel only"):
            make_train_step(model.loss, optim.sgd(0.1), mesh_2d,
                            mode="explicit")

    def test_implicit_explicit_equivalence(self, mesh8):
        """The GSPMD-inserted all-reduce and the literal shard_map psum must
        produce identical updates (both are 'psum data-parallel')."""
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        batch_np = (np.random.default_rng(0).random((16, 784), np.float32),
                    np.eye(10, dtype=np.float32)[np.arange(16) % 10])
        rng = jax.random.key(0)

        results = {}
        for mode in ("implicit", "explicit"):
            state = init_state(model, opt, seed=1, mesh=mesh8)
            step = make_train_step(model.loss, opt, mesh8, mode=mode,
                                   donate=False)
            batch = put_global_batch(mesh8, batch_np)
            state, metrics = step(state, batch, rng)
            results[mode] = (state, metrics)

        pa = results["implicit"][0]["params"]
        pb = results["explicit"][0]["params"]
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                    rtol=2e-5, atol=1e-6),
            pa, pb)
        assert float(results["implicit"][1]["loss"]) == pytest.approx(
            float(results["explicit"][1]["loss"]), rel=2e-5)

    def test_step_is_deterministic(self, mesh8):
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        batch_np = (np.random.default_rng(0).random((16, 784), np.float32),
                    np.eye(10, dtype=np.float32)[np.arange(16) % 10])

        losses = []
        for _ in range(2):
            state = init_state(model, opt, seed=1, mesh=mesh8)
            step = make_train_step(model.loss, opt, mesh8, donate=False)
            _, m = step(state, put_global_batch(mesh8, batch_np),
                        jax.random.key(0))
            losses.append(float(m["loss"]))
        assert losses[0] == losses[1]

    def test_global_step_counts_sync_updates(self, mesh8):
        """global_step semantics: the reference counted every async worker
        apply (tf_distributed.py:39,75-76); here one step == one global
        update."""
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        state = init_state(model, opt, seed=1, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, donate=False)
        batch = put_global_batch(
            mesh8, (np.zeros((8, 784), np.float32),
                    np.eye(10, dtype=np.float32)[np.arange(8) % 10]))
        for i in range(3):
            state, _ = step(state, batch, jax.random.key(i))
        assert int(state["step"]) == 3


class TestTrainerEndToEnd:
    def test_mnist_converges_and_logs_contract(self, mesh8, small_cfg, capsys):
        """End-to-end: synthetic MNIST in a falsifiable band (the
        multimodal/label-noise task caps at ~0.93, so both bounds can
        trip), console lines match the reference format.  Three adam
        epochs — plain 1-epoch SGD no longer saturates the hard task,
        which is the point of it."""
        cluster = make_cluster(mesh8)
        model = MnistMLP(init_scale="fan_in")
        trainer = Trainer(cluster, model, optim.adam(1e-3), small_cfg)
        splits = load_mnist(seed=1)
        result = trainer.fit(splits, epochs=3)
        assert 0.60 < result["test_accuracy"] < 0.96   # measured 0.927
        out = capsys.readouterr().out
        assert re.search(r"Step: \d+, {2}Epoch: +\d+, {2}Batch: +\d+ of +\d+, "
                         r" Cost: \d+\.\d{4}, {2}AvgTime: +\d+\.\d{2}ms", out)
        assert re.search(r"Test-Accuracy: \d+\.\d{2}", out)
        assert re.search(r"Total Time: +\d+\.\d{2}s", out)
        assert re.search(r"Final Cost: \d+\.\d{4}", out)

    def test_metrics_csv_written(self, mesh8, small_cfg, tmp_path):
        cluster = make_cluster(mesh8)
        trainer = Trainer(cluster, MnistMLP(init_scale="fan_in"),
                          optim.sgd(0.05), small_cfg)
        trainer.fit(load_mnist(seed=1), epochs=1)
        trainer.logger.close()
        csv_path = tmp_path / "metrics.csv"
        assert csv_path.exists()
        content = csv_path.read_text()
        assert "cost" in content and "test_accuracy" in content

    def test_reference_format_golden(self):
        line = format_step_line(100, 1, 100, 500, 1.2345, 12.34)
        assert line == "Step: 100,  Epoch:  1,  Batch: 100 of 500,  Cost: 1.2345,  AvgTime: 12.34ms"


class TestGradAccumulation:
    def test_matches_full_batch_step(self, mesh8):
        """grad of a mean == mean of microbatch grads: one accumulated step
        must equal the full-batch step to float tolerance."""
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]
        out = {}
        for accum in (1, 4):
            state = init_state(model, opt, seed=1, mesh=mesh8)
            step = make_train_step(model.loss, opt, mesh8, donate=False,
                                   grad_accum=accum)
            batch = put_global_batch(mesh8, (x, y))
            state, metrics = step(state, batch, jax.random.key(0))
            out[accum] = (jax.device_get(state["params"]),
                          float(metrics["loss"]))
        assert out[1][1] == pytest.approx(out[4][1], abs=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(out[1][0]),
                        jax.tree_util.tree_leaves(out[4][0])):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_stateful_model_threads_bn_stats(self, mesh8):
        """ResNet (BatchNorm) with accumulation: runs and updates stats."""
        from dtf_tpu.models.resnet import ResNet, ResNetConfig

        model = ResNet(ResNetConfig.tiny())
        opt = optim.sgd(0.05)
        state = init_state(model, opt, seed=0, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, stateful=True,
                               donate=False, grad_accum=2)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
        state, metrics = step(state, put_global_batch(mesh8, (x, y)),
                              jax.random.key(0))
        assert np.isfinite(float(metrics["loss"]))
        assert not np.allclose(
            np.asarray(state["model_state"]["stem_bn"]["mean"]), 0.0)

    def test_indivisible_batch_fails_loudly(self, mesh8):
        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)
        state = init_state(model, opt, seed=1, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, donate=False,
                               grad_accum=3)
        batch = put_global_batch(
            mesh8, (np.zeros((64, 784), np.float32),
                    np.zeros((64, 10), np.float32)))
        with pytest.raises(Exception):
            step(state, batch, jax.random.key(0))    # 64 % 3 != 0
