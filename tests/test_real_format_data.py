"""Real-bytes data path, end to end: fixture writers emit the genuine
on-disk formats (IDX, CIFAR pickle batches), the loaders parse them
through their real-file code paths (not the synthetic fallback), and
MNIST trains into a falsifiable sub-1.0 accuracy band on those bytes
(the synthetic content carries label noise, so 1.00 is unreachable)."""

import jax
import numpy as np
import pytest

from dtf_tpu.data.datasets import load_cifar10, load_mnist
from dtf_tpu.data.fixtures import write_cifar_batches, write_mnist_idx


class TestMnistIdx:
    def test_round_trip_plain_and_gzip(self, tmp_path):
        for compress in (False, True):
            d = tmp_path / ("gz" if compress else "plain")
            write_mnist_idx(str(d), n_train=256, n_test=64,
                            compress=compress)
            splits = load_mnist(str(d))
            assert not splits.synthetic          # real-file path taken
            assert splits.train.images.shape == (256, 784)
            assert splits.test.images.shape == (64, 784)
            assert splits.train.images.dtype == np.float32
            assert 0.0 <= splits.train.images.min()
            assert splits.train.images.max() <= 1.0
            assert splits.train.labels.shape == (256, 10)
            assert np.all(splits.train.labels.sum(axis=1) == 1.0)

    def test_trains_into_falsifiable_band(self, tmp_path, mesh8):
        """The reference's observable: real-bytes MNIST reaching high test
        accuracy (tf_distributed.py:126).  Adam for a CPU-friendly step
        budget; the content is the UNSATURABLE multimodal/label-noise
        synthetic task in real IDX clothing — the asserted band has a
        ceiling BELOW 1.0 (the 8% label flips cap accuracy at ~0.93), so
        this number can regress in either direction: a broken optimizer
        falls out the bottom, an accidentally-trivial task breaks the
        top."""
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        write_mnist_idx(str(tmp_path), n_train=2048, n_test=512)
        splits = load_mnist(str(tmp_path))
        assert not splits.synthetic
        model = MnistMLP(init_scale="fan_in")
        opt = optim.adam(1e-3)
        state = init_state(model, opt, seed=1, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, donate=False)
        for i in range(300):
            batch = put_global_batch(mesh8, splits.train.next_batch(128))
            state, _ = step(state, batch, jax.random.key(i))
        import jax.numpy as jnp
        logits = model.apply(state["params"],
                             jnp.asarray(splits.test.images))
        acc = float(np.mean(np.argmax(logits, -1)
                            == np.argmax(splits.test.labels, -1)))
        assert 0.80 <= acc <= 0.96, acc   # measured 0.908 at n=2048


class TestCifarPickles:
    def test_round_trip(self, tmp_path):
        write_cifar_batches(str(tmp_path), n_per_batch=64, n_test=32)
        splits = load_cifar10(str(tmp_path))
        assert not splits.synthetic
        assert splits.train.images.shape == (320, 32, 32, 3)
        assert splits.test.images.shape == (32, 32, 32, 3)
        assert 0.0 <= splits.train.images.min()
        assert splits.train.images.max() <= 1.0
        assert splits.train.labels.shape == (320, 10)

    def test_channel_layout_preserved(self, tmp_path):
        """The pickle rows are channel-planar (R plane, G plane, B plane);
        the loader must unscramble them back to (H, W, C)."""
        import pickle

        write_cifar_batches(str(tmp_path), n_per_batch=8, n_test=8)
        with open(tmp_path / "data_batch_1", "rb") as f:
            raw = pickle.load(f, encoding="bytes")
        row = np.asarray(raw[b"data"][0], np.float32) / 255.0
        want = row.reshape(3, 32, 32).transpose(1, 2, 0)
        splits = load_cifar10(str(tmp_path))
        np.testing.assert_allclose(splits.train.images[0], want,
                                   atol=1e-6)
