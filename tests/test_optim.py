"""Optimizers beyond the reference's plain SGD: adafactor's factored
second-moment state (memory) and convergence, LAMB's trust-ratio updates,
and both inside a sharded train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu import optim


def quad_grads(params):
    """Gradient of 0.5*||p - target||^2 per leaf (target = 3)."""
    return jax.tree_util.tree_map(lambda p: p - 3.0, params)


class TestAdafactor:
    def test_factored_state_is_small(self):
        params = {"w": jnp.zeros((256, 512)), "b": jnp.zeros((512,))}
        opt = optim.adafactor(1e-2)
        state = opt.init(params)
        slot_w = state["slots"]["w"]
        assert set(slot_w) == {"vr", "vc"}
        assert slot_w["vr"].shape == (256,)
        assert slot_w["vc"].shape == (512,)
        # vs Adam's v: 256*512 floats -> 256+512
        assert (slot_w["vr"].size + slot_w["vc"].size) == 768
        # small/1-D tensors keep the full second moment
        assert state["slots"]["b"]["v"].shape == (512,)

    def test_small_matrix_unfactored(self):
        params = {"w": jnp.zeros((16, 16))}
        state = optim.adafactor(1e-2).init(params)
        assert "v" in state["slots"]["w"]

    def test_stacked_layer_dims_factor_trailing_two(self):
        params = {"w": jnp.zeros((4, 256, 512))}      # (layers, in, out)
        state = optim.adafactor(1e-2).init(params)
        assert state["slots"]["w"]["vr"].shape == (4, 256)
        assert state["slots"]["w"]["vc"].shape == (4, 512)

    def test_converges_on_quadratic(self):
        params = {"w": jnp.full((256, 256), 10.0), "b": jnp.zeros((8,))}
        opt = optim.adafactor(0.3)
        state = opt.init(params)
        for _ in range(60):
            upd, state = opt.update(quad_grads(params), state, params)
            params = optim.apply_updates(params, upd)
        err = float(jnp.max(jnp.abs(params["w"] - 3.0)))
        assert err < 0.5, err
        assert float(jnp.max(jnp.abs(params["b"] - 3.0))) < 0.5

    def test_trains_mlp(self, mesh8):
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)
        model = MnistMLP(init_scale="fan_in")
        opt = optim.adafactor(1e-2)
        state = init_state(model, opt, seed=1, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, donate=False)
        rng = np.random.default_rng(0)
        batch = put_global_batch(
            mesh8, (rng.random((64, 784), np.float32),
                    np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)]))
        losses = []
        for i in range(10):
            state, m = step(state, batch, jax.random.key(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


class TestLamb:
    def test_trust_ratio_scales_per_tensor(self):
        """Layers with different weight norms get different effective step
        sizes (that is the point of LAMB)."""
        params = {"big": jnp.full((32, 32), 10.0),
                  "small": jnp.full((32, 32), 0.1)}
        opt = optim.lamb(1e-2, weight_decay=0.0)
        state = opt.init(params)
        grads = jax.tree_util.tree_map(jnp.ones_like, params)
        upd, _ = opt.update(grads, state, params)
        step_big = float(jnp.mean(jnp.abs(upd["big"])))
        step_small = float(jnp.mean(jnp.abs(upd["small"])))
        assert step_big > step_small * 10     # ~ ||p|| ratio (100x)

    def test_converges_on_quadratic(self):
        params = {"w": jnp.full((64, 64), 10.0)}
        opt = optim.lamb(0.05, weight_decay=0.0)
        state = opt.init(params)
        for _ in range(200):
            upd, state = opt.update(quad_grads(params), state, params)
            params = optim.apply_updates(params, upd)
        assert float(jnp.max(jnp.abs(params["w"] - 3.0))) < 0.5

    def test_trains_bert_tiny(self, mesh8):
        from dtf_tpu.data.datasets import synthetic_text
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)
        model = BertMLM(BertConfig.tiny())
        opt = optim.lamb(1e-2, weight_decay=0.0)
        state = init_state(model, opt, seed=0, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, donate=False)
        toks = synthetic_text(16, 32, 128, seed=1)
        losses = []
        for _ in range(10):
            # fixed rng: same MLM mask each step, so the descent signal
            # isn't buried in per-step masking noise
            state, m = step(state, put_global_batch(mesh8, toks),
                            jax.random.key(0))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]


class TestSchedulesStillCompose:
    def test_adafactor_with_schedule(self):
        sched = optim.warmup_cosine(0.1, 5, 50)
        params = {"w": jnp.full((256, 256), 10.0)}
        opt = optim.adafactor(sched)
        state = opt.init(params)
        upd, state = opt.update(quad_grads(params), state, params)
        assert np.isfinite(float(jnp.sum(upd["w"])))


class TestTupleContainers:
    def test_adafactor_handles_tuple_param_trees(self):
        """Tuple containers in the params pytree must not be mistaken for
        internal (update, slot) pairs during the unzip."""
        params = ({"w": jnp.full((256, 256), 10.0)},
                  {"w": jnp.full((256, 256), 10.0)})
        opt = optim.adafactor(0.3)
        state = opt.init(params)
        upd, state = opt.update(quad_grads(params), state, params)
        assert isinstance(upd, tuple) and len(upd) == 2
        assert upd[0]["w"].shape == (256, 256)
        assert upd[1]["w"].shape == (256, 256)
        new = optim.apply_updates(params, upd)   # structure must match
        assert new[1]["w"].shape == (256, 256)


class TestRegistry:
    def test_get_known_and_unknown(self):
        assert optim.get("adafactor") is optim.adafactor
        with pytest.raises(ValueError, match="adafactor.*nadam"):
            optim.get("nadam")


class TestSchedulesEverywhere:
    @pytest.mark.parametrize("name", sorted(optim.BY_NAME))
    def test_every_optimizer_accepts_a_schedule(self, name):
        """--lr_schedule cosine must work with every --optimizer choice."""
        sched = optim.warmup_cosine(0.1, 2, 20)
        params = {"w": jnp.full((8, 8), 10.0)}
        opt = optim.BY_NAME[name](sched)
        state = opt.init(params)
        p = params
        for _ in range(3):
            upd, state = opt.update(quad_grads(p), state, p)
            p = optim.apply_updates(p, upd)
        assert np.isfinite(float(jnp.sum(p["w"])))
        assert not np.array_equal(np.asarray(p["w"]), np.asarray(params["w"]))

    def test_warmup_actually_ramps(self):
        sched = optim.warmup_cosine(1.0, warmup_steps=10, total_steps=100)
        import jax.numpy as _jnp
        lrs = [float(sched(_jnp.asarray(s))) for s in (1, 5, 10, 55, 100)]
        assert lrs[0] < lrs[1] < lrs[2]          # ramp
        assert lrs[2] == pytest.approx(1.0, abs=0.1)
        assert lrs[3] < lrs[2] and lrs[4] < lrs[3]   # cosine decay
