"""Child for the 2-process prefetch A/B (tests/test_prefetch.py): run a
short Trainer.fit with the given --prefetch depth over the per-host
sharded data path (``Dataset.process_shard`` + ``put_process_batch``).
The coordinator's metrics.csv carries the per-step cost rows; the parent
asserts they are bitwise-identical between prefetch 0 and prefetch 2 —
the exact-trajectory proof in the true multi-process configuration.

Usage: _mp_prefetch.py <task> <coordinator> <prefetch> <logdir>
"""

import sys


def main() -> int:
    task, coord = int(sys.argv[1]), sys.argv[2]
    prefetch, logdir = int(sys.argv[3]), sys.argv[4]
    import jax
    jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                               process_id=task)

    from dtf_tpu import optim
    from dtf_tpu.cluster import Cluster
    from dtf_tpu.config import ClusterConfig, TrainConfig
    from dtf_tpu.data import load_mnist
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.parallel.mesh import make_mesh
    from dtf_tpu.train.trainer import Trainer

    mesh = make_mesh("data=-1")
    cfg = TrainConfig(batch_size=64, learning_rate=0.05, epochs=1,
                      log_frequency=1, seed=1, logdir=logdir,
                      prefetch=prefetch)
    cluster = Cluster(config=ClusterConfig(task_index=task,
                                           num_processes=2), mesh=mesh)
    trainer = Trainer(cluster, MnistMLP(init_scale="fan_in"),
                      optim.sgd(0.05), cfg)
    res = trainer.fit(load_mnist(seed=1), epochs=1, max_steps=6)
    trainer.logger.close()
    print(f"MP_PREFETCH_DONE steps={res['steps']} "
          f"final_cost={res['final_cost']!r}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
