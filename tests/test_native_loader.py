"""Native C++ data loader: builds with the system toolchain, parses IDX,
prefetches correct batches matching the Python loader's contract."""

import struct

import numpy as np
import pytest

from dtf_tpu.data.datasets import _read_idx
from dtf_tpu.data.native_loader import NativeDataset


def write_idx(path, arr: np.ndarray) -> None:
    """Write a uint8 array in IDX format (the MNIST container)."""
    arr = np.ascontiguousarray(arr, np.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, arr.ndim))
        for d in arr.shape:
            f.write(struct.pack(">I", d))
        f.write(arr.tobytes())


@pytest.fixture(scope="module")
def idx_files(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("idx")
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (64, 5, 5), dtype=np.uint8)
    labels = rng.integers(0, 10, (64,), dtype=np.uint8)
    ip, lp = str(tmp / "imgs.idx"), str(tmp / "labs.idx")
    write_idx(ip, images)
    write_idx(lp, labels)
    return ip, lp, images, labels


class TestNativeLoader:
    def test_builds_and_opens(self, idx_files):
        ip, lp, images, labels = idx_files
        ds = NativeDataset.from_idx(ip, lp, batch_size=16, seed=7)
        assert ds is not None, "native loader failed to build/open"
        assert ds.num_examples == 64
        assert ds.feature_dim == 25
        ds.close()

    def test_idx_writer_roundtrip(self, idx_files):
        ip, lp, images, labels = idx_files
        np.testing.assert_array_equal(_read_idx(ip), images)
        np.testing.assert_array_equal(_read_idx(lp), labels)

    def test_epoch_covers_all_examples_once(self, idx_files):
        ip, lp, images, labels = idx_files
        ds = NativeDataset.from_idx(ip, lp, batch_size=16, seed=3)
        seen = []
        for _ in range(64 // 16):          # one epoch
            imgs, labs = ds.next_batch(16)
            assert imgs.shape == (16, 25) and labs.shape == (16, 10)
            assert imgs.min() >= 0.0 and imgs.max() <= 1.0
            seen.append(imgs)
        ds.close()
        got = np.concatenate(seen) * 255.0
        want = images.reshape(64, 25).astype(np.float32)
        # same multiset of rows: each example exactly once per epoch
        got_sorted = got[np.lexsort(got.T)]
        want_sorted = want[np.lexsort(want.T)]
        np.testing.assert_allclose(got_sorted, want_sorted, atol=1e-4)

    def test_labels_one_hot_match_images(self, idx_files):
        ip, lp, images, labels = idx_files
        ds = NativeDataset.from_idx(ip, lp, batch_size=64, seed=5)
        imgs, labs = ds.next_batch(64)
        ds.close()
        assert (labs.sum(axis=1) == 1.0).all()
        # map each produced row back to its source index; labels must match
        flat = images.reshape(64, 25).astype(np.float32) / 255.0
        for i in range(64):
            src = np.argmin(np.abs(flat - imgs[i]).sum(axis=1))
            assert labs[i, labels[src]] == 1.0

    def test_shuffles_between_epochs_deterministically(self, idx_files):
        ip, lp, *_ = idx_files
        def epoch_order(seed):
            ds = NativeDataset.from_idx(ip, lp, batch_size=64, seed=seed)
            imgs, _ = ds.next_batch(64)
            ds.close()
            return imgs
        a1, a2 = epoch_order(11), epoch_order(11)
        b = epoch_order(12)
        np.testing.assert_array_equal(a1, a2)      # same seed -> same order
        assert not np.array_equal(a1, b)           # different seed differs

    def test_wrong_batch_size_raises(self, idx_files):
        ip, lp, *_ = idx_files
        ds = NativeDataset.from_idx(ip, lp, batch_size=16)
        with pytest.raises(ValueError, match="fixed batches"):
            ds.next_batch(32)
        with pytest.raises(ValueError, match="fixed batches"):
            ds.fast_forward(2, 32)
        ds.close()

    def test_fast_forward_matches_drained_stream(self, idx_files):
        """fast_forward(n) must leave the shuffle stream exactly where n
        next_batch calls would (same C++ prefetch stream), while reusing
        one scratch buffer pair instead of allocating per batch."""
        ip, lp, *_ = idx_files
        a = NativeDataset.from_idx(ip, lp, batch_size=16, seed=5)
        b = NativeDataset.from_idx(ip, lp, batch_size=16, seed=5)
        want = [a.next_batch(16) for _ in range(4)][3]
        b.fast_forward(3, 16)
        got = b.next_batch(16)
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])
        assert b.batches_consumed == 4
        b.fast_forward(0, 16)                  # no-op, no validation crash
        assert b.batches_consumed == 4
        a.close()
        b.close()

    def test_bad_path_returns_none(self):
        assert NativeDataset.from_idx("/nonexistent/a", "/nonexistent/b",
                                      batch_size=4) is None

    def test_trains_mnist_mlp(self, idx_files, mesh8):
        """NativeDataset drives the real trainer loop."""
        import jax
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        ip, lp, *_ = idx_files
        ds = NativeDataset.from_idx(ip, lp, batch_size=16, seed=1)
        model = MnistMLP(init_scale="fan_in", in_dim=25)
        opt = optim.sgd(0.1)
        state = init_state(model, opt, seed=1, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, donate=False)
        for i in range(4):
            batch = put_global_batch(mesh8, ds.next_batch(16))
            state, metrics = step(state, batch, jax.random.key(i))
        ds.close()
        assert np.isfinite(float(metrics["loss"]))
