"""The dependency-free TensorBoard event writer: wire-format correctness
(validated against stock tensorboard's own EventFileLoader), round-trip via
the bundled reader, crc integrity, and MetricLogger integration.

The reference wrote cost/accuracy scalar summaries to a TensorBoard logdir
every step (tf_distributed.py:84-88,97,111-112); this is that capability
without a TensorFlow dependency.
"""

import glob
import struct

import numpy as np
import pytest

from dtf_tpu.train.metrics import MetricLogger
from dtf_tpu.train.tbevents import (TBEventWriter, _crc32c, _masked_crc,
                                    read_scalars)


class TestCrc32c:
    def test_known_vectors(self):
        # RFC 3720 / kernel test vectors for crc32c (Castagnoli).
        assert _crc32c(b"") == 0
        assert _crc32c(b"123456789") == 0xE3069283
        assert _crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_mask_is_invertible_offset(self):
        c = _crc32c(b"hello")
        m = _masked_crc(b"hello")
        unrot = (m - 0xA282EAD8) & 0xFFFFFFFF
        assert (((unrot << 15) | (unrot >> 17)) & 0xFFFFFFFF) == c


class TestWriterReader:
    def test_round_trip(self, tmp_path):
        w = TBEventWriter(str(tmp_path))
        w.scalar(1, "cost", 2.5)
        w.scalar(2, "cost", 1.25)
        w.scalar(2, "accuracy", 0.5)
        w.close()
        assert read_scalars(w.path) == [
            (1, "cost", 2.5), (2, "cost", 1.25), (2, "accuracy", 0.5)]

    def test_torn_tail_returns_parsed_prefix(self, tmp_path):
        """A record truncated mid-write (hard kill during flush) reads as
        EOF — the scalars already on disk survive for post-mortem."""
        w = TBEventWriter(str(tmp_path))
        w.scalar(1, "cost", 2.5)
        w.scalar(2, "cost", 1.25)
        w.close()
        data = open(w.path, "rb").read()
        for cut in (3, 7, 11):     # mid-header, mid-crc, mid-payload
            open(w.path, "wb").write(data[:-cut])
            assert read_scalars(w.path) == [(1, "cost", 2.5)]

    def test_corrupt_record_detected(self, tmp_path):
        w = TBEventWriter(str(tmp_path))
        w.scalar(1, "cost", 2.5)
        w.close()
        data = bytearray(open(w.path, "rb").read())
        data[-5] ^= 0xFF            # flip a payload byte
        open(w.path, "wb").write(bytes(data))
        with pytest.raises(ValueError, match="crc"):
            read_scalars(w.path)

    def test_stock_tensorboard_reads_our_files(self, tmp_path):
        """The real consumer: tensorboard's EventFileLoader must parse the
        file and recover every scalar."""
        loader_mod = pytest.importorskip(
            "tensorboard.backend.event_processing.event_file_loader")
        w = TBEventWriter(str(tmp_path))
        steps = [(1, "cost", 2.5), (100, "cost", 0.125), (100, "acc", 0.75)]
        for s, tag, v in steps:
            w.scalar(s, tag, v)
        w.close()

        got = []
        for ev in loader_mod.LegacyEventFileLoader(w.path).Load():
            for val in ev.summary.value:
                got.append((ev.step, val.tag, val.simple_value))
        assert got == steps

    def test_stock_tensorboard_parses_our_graph_event(self, tmp_path):
        """The GraphDef event (reference: writer.add_graph at Supervisor
        startup, tf_distributed.py:97) must decode into a real GraphDef
        with our node names, ops, and inputs."""
        pytest.importorskip(
            "tensorboard.backend.event_processing.event_file_loader")
        from tensorboard.backend.event_processing import event_file_loader
        from tensorboard.compat.proto import graph_pb2

        w = TBEventWriter(str(tmp_path))
        w.graph([("model/layer0/w", "Parameter[4x8]", ()),
                 ("model", "Model", ("model/layer0/w",))])
        w.scalar(1, "cost", 0.5)
        w.close()

        graphs = []
        for ev in event_file_loader.LegacyEventFileLoader(w.path).Load():
            if ev.HasField("graph_def"):
                gd = graph_pb2.GraphDef()
                gd.ParseFromString(ev.graph_def)
                graphs.append(gd)
        assert len(graphs) == 1
        by_name = {n.name: n for n in graphs[0].node}
        assert by_name["model/layer0/w"].op == "Parameter[4x8]"
        assert list(by_name["model"].input) == ["model/layer0/w"]

    def test_graph_from_params_covers_every_leaf(self, tmp_path):
        import numpy as np

        w = TBEventWriter(str(tmp_path))
        params = {"enc": {"w": np.zeros((2, 3)), "b": np.zeros((3,))},
                  "head": np.zeros((3, 4))}
        w.graph_from_params(params, root="m")
        w.close()
        data = open(w.path, "rb").read()
        assert b"m/enc/w" in data and b"m/enc/b" in data
        assert b"m/head" in data and b"Parameter[2x3]" in data

    def test_reader_reads_tensorboard_written_files(self, tmp_path):
        """Symmetry: our reader parses files written by the stock tb.summary
        writer (guards against a writer+reader that agree only with each
        other)."""
        tbsw = pytest.importorskip("tensorboard.summary.writer.event_file_writer")
        ef = tbsw.EventFileWriter(str(tmp_path))
        from tensorboard.compat.proto import event_pb2, summary_pb2
        ev = event_pb2.Event(step=7, wall_time=1.0)
        ev.summary.value.add(tag="loss", simple_value=0.5)
        ef.add_event(ev)
        ef.close()
        (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
        assert (7, "loss", 0.5) in read_scalars(path)


class TestMetricLoggerIntegration:
    def test_logger_writes_event_file(self, tmp_path):
        logger = MetricLogger(str(tmp_path), is_coordinator=True, quiet=True)
        logger.scalar(1, "cost", 3.0)
        logger.scalar(2, "cost", 2.0)
        logger.close()
        (path,) = glob.glob(str(tmp_path / "events.out.tfevents.*"))
        assert read_scalars(path) == [(1, "cost", 3.0), (2, "cost", 2.0)]

    def test_non_coordinator_writes_nothing(self, tmp_path):
        logger = MetricLogger(str(tmp_path), is_coordinator=False, quiet=True)
        logger.scalar(1, "cost", 3.0)
        logger.close()
        assert glob.glob(str(tmp_path / "events.out.tfevents.*")) == []
