"""Incident plane (telemetry/anomaly.py + telemetry/diagnose.py, ISSUE 18).

The honesty pins live here:

* **detector edges** — cold start is silence; a step function fires
  exactly once (edge, not level) and re-arms after the baseline
  migrates; an all-constant signal (MAD = 0) neither divides by zero
  nor fires on float noise; a steady ramp is NOT an anomaly;
* **clock independence** — the math is values-only, so the same
  observation sequence fires identically whether wall time passes
  between observations or not (VirtualClock/WallClock parity);
* **attribution falsifiability** — temporal precedence excludes
  post-anomaly evidence, the chaos plane out-ranks innocents, and an
  inverted-priors correlator (deliberately blaming an innocent plane)
  demonstrably FAILS the ``min_attribution_frac`` gate — as does
  chaos-fired-with-nothing-detected (frac None = not measured);
* **bounded live state** — the incident ring evicts oldest-first with
  an honest evicted count;
* **standing incidents** — a trailing bench-ledger error streak
  surfaces as one incident; a recovered ledger does not.
"""

import json
import os
import time
import urllib.error
import urllib.request

import pytest

import dtf_tpu.telemetry as tel
from dtf_tpu.telemetry import anomaly, diagnose
from dtf_tpu.telemetry.anomaly import AnomalyMonitor, RollingDetector
from dtf_tpu.telemetry.diagnose import (IncidentRing, attribution_summary,
                                        classify, correlate,
                                        diagnose_logdir, diagnose_records,
                                        ledger_standing_incidents)
from dtf_tpu.telemetry.live import AdminServer
from dtf_tpu.telemetry.report import check_gates


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    tel.reset()
    yield
    tel.reset()


def _detector(**over):
    cfg = dict(window=16, min_samples=4, threshold=6.0, rel_floor=0.25,
               abs_floor=1.0)
    cfg.update(over)
    return RollingDetector("test/sig", **cfg)


# ---------------------------------------------------------------------------
# detector math edges


class TestRollingDetector:
    def test_cold_start_never_fires(self):
        det = _detector(min_samples=8)
        # wild values, but fewer than min_samples seen: always silence
        for v in (1.0, 500.0, -300.0, 1e6, 0.0, 42.0, 7e5):
            assert det.observe(v) is None
        assert det.fired_total == 0

    def test_step_function_fires_exactly_once(self):
        det = _detector()
        fires = [det.observe(10.0) for _ in range(10)]
        fires += [det.observe(100.0) for _ in range(20)]
        docs = [f for f in fires if f]
        # the onset fires; the PERSISTING level does not re-fire
        assert len(docs) == 1
        assert docs[0]["value"] == 100.0 and docs[0]["z"] >= 6.0

    def test_rearms_after_baseline_migrates(self):
        det = _detector()
        for _ in range(10):
            det.observe(10.0)
        first = [det.observe(100.0) for _ in range(20)]
        # window is now all-100s: the detector re-armed, so a SECOND
        # edge fires again — once
        second = [det.observe(400.0) for _ in range(20)]
        assert sum(1 for f in first if f) == 1
        assert sum(1 for f in second if f) == 1
        assert det.fired_total == 2

    def test_constant_signal_mad_zero_no_fire(self):
        det = _detector(abs_floor=1.0)
        for _ in range(30):
            assert det.observe(5.0) is None          # no div-by-zero
        # float-noise wiggle under the abs_floor: still silence
        for i in range(30):
            assert det.observe(5.0 + 1e-9 * (i % 3)) is None
        assert det.fired_total == 0

    def test_steady_ramp_is_not_an_anomaly(self):
        det = _detector()
        fires = [det.observe(10.0 + 3.0 * i) for i in range(64)]
        # MAD grows with the ramp, so z stays near 1: no changepoint
        assert not any(fires)

    def test_clock_parity_values_only(self):
        """VirtualClock/WallClock parity: identical observation
        sequences fire identically whether or not wall time elapses
        between observations — the math never reads a clock."""
        seq = [10.0] * 8 + [90.0] * 4 + [10.0] * 8 + [250.0] * 3
        fast, slow = _detector(), _detector()
        fired_fast = [i for i, v in enumerate(seq) if fast.observe(v)]
        fired_slow = []
        for i, v in enumerate(seq):
            time.sleep(0.002)          # wall-clock gaps, virtual has none
            if slow.observe(v):
                fired_slow.append(i)
        assert fired_fast == fired_slow and fired_fast


class TestAnomalyMonitor:
    def test_fire_books_counter_instant_and_incident(self, tmp_path):
        tel.configure(str(tmp_path))
        mon = anomaly.get_monitor().arm()
        diagnose.install()
        tel.instant("chaos/slow_decode", step=3)
        for _ in range(8):
            mon.observe("serve/ttft_ms", 20.0)
        # signal config for ttft has min_samples=16: use the default-
        # config signal instead for a short warmup
        for _ in range(16):
            mon.observe("custom/sig", 20.0)
        fired = mon.observe("custom/sig", 2000.0)
        assert fired and fired["signal"] == "custom/sig"
        snap = tel.get_registry().snapshot()
        assert snap["anomaly/detected_total"]["value"] == 1
        assert snap["incident/recorded_total"]["value"] == 1
        assert snap["incident/attributed_total"]["value"] == 1
        inc = diagnose.get_ring().snapshot()["incidents"]
        assert len(inc) == 1
        assert inc[0]["top"]["kind"] == "slow_decode"
        # the instant landed in the span file for the post-hoc path
        tel.get_tracer().flush()
        doc = diagnose_logdir(str(tmp_path))
        assert doc["anomalies"] == 1 and doc["attribution_frac"] == 1.0

    def test_armed_counter_is_eager_zero(self):
        AnomalyMonitor().arm()
        snap = tel.get_registry().snapshot()
        assert snap["anomaly/detected_total"]["value"] == 0

    def test_reset_baselines_forgets_windows(self):
        mon = AnomalyMonitor()
        for _ in range(20):
            mon.observe("custom/sig", 10.0)
        mon.reset_baselines()
        # post-reset the window is cold again: a wild value is silence
        assert mon.observe("custom/sig", 1e6) is None


# ---------------------------------------------------------------------------
# live ring


class TestIncidentRing:
    def test_eviction_order_and_counts(self):
        ring = IncidentRing(maxlen=4)
        for i in range(10):
            ring.push({"anomaly": {"name": f"a{i}"}})
        snap = ring.snapshot()
        assert snap["total"] == 10 and snap["evicted"] == 6
        # oldest evicted first: the survivors are the LAST four, in
        # push order, with their original seq numbers
        assert [i["seq"] for i in snap["incidents"]] == [6, 7, 8, 9]
        assert [i["anomaly"]["name"] for i in snap["incidents"]] == \
            ["a6", "a7", "a8", "a9"]


# ---------------------------------------------------------------------------
# correlator


def _ev(name, ts_s, **args):
    return {"name": name, "ts": ts_s * 1e6, "args": args}


class TestCorrelate:
    def test_precedence_excludes_post_anomaly_evidence(self):
        events = [_ev("chaos/slow_decode", 100.0),
                  _ev("chaos/kv_poison", 103.0)]   # AFTER the anomaly
        sus = correlate(102.0 * 1e6, events)
        assert [s["kind"] for s in sus] == ["slow_decode"]

    def test_window_excludes_stale_evidence(self):
        events = [_ev("chaos/slow_decode", 10.0)]
        assert correlate(100.0 * 1e6, events, window_s=60.0) == []

    def test_chaos_outranks_innocent_planes(self):
        events = [_ev("chaos/slow_decode", 90.0),
                  _ev("event/brownout_transition", 99.0, new=1),
                  _ev("event/slo_alert_ttft_fast", 99.5)]
        sus = correlate(100.0 * 1e6, events)
        assert sus[0]["kind"] == "slow_decode"
        # ...even though the innocents are MORE recent
        assert sus[0]["dt_s"] > sus[1]["dt_s"]

    def test_one_suspect_per_kind_latest_carries_evidence(self):
        events = [_ev("control/set", 95.0, knob="spec_k", value=2),
                  _ev("control/set", 99.0, knob="spec_k", value=4)]
        sus = correlate(100.0 * 1e6, events)
        assert len(sus) == 1
        assert sus[0]["count"] == 2
        assert sus[0]["evidence"]["value"] == 4

    def test_anomaly_instants_are_never_evidence(self):
        assert classify("anomaly/serve_ttft_ms") is None
        events = [_ev("anomaly/serve_tpot_ms", 99.0)]
        assert correlate(100.0 * 1e6, events) == []


# ---------------------------------------------------------------------------
# attribution semantics + the gate's falsifiability


def _rec(name, ts_s, **args):
    return {"ph": "i", "name": name, "ts": ts_s * 1e6, "args": args}


class TestAttribution:
    def test_chaos_top_counts_attributed(self):
        recs = [_rec("chaos/slow_decode", 90.0),
                _rec("anomaly/serve_ttft_ms", 95.0, z=12.0)]
        doc = diagnose_records(recs)
        assert doc["chaos_fired"] and doc["attribution_frac"] == 1.0
        assert doc["top_plane_counts"] == {"chaos": 1}

    def test_injected_but_undetected_is_not_measured(self):
        recs = [_rec("chaos/slow_decode", 90.0)]   # zero anomalies
        doc = diagnose_records(recs)
        assert doc["chaos_fired"] and doc["attribution_frac"] is None
        ok, lines = check_gates({"incidents": doc},
                                min_attribution_frac=0.5)
        assert not ok and "not measured" in lines[0]

    def test_innocent_blaming_correlator_fails_the_gate(self):
        """The falsifiability pin: invert the priors so the SLO plane
        out-ranks chaos — the anomaly is still 'attributed' to SOME
        plane, but the gate demands the injected fault be TOP."""
        recs = [_rec("chaos/slow_decode", 94.0),
                _rec("event/slo_alert_ttft_fast", 94.5),
                _rec("anomaly/serve_ttft_ms", 95.0, z=12.0)]
        honest = diagnose_records(recs)
        assert honest["attribution_frac"] == 1.0
        assert check_gates({"incidents": honest},
                           min_attribution_frac=0.99)[0]
        inverted = tuple((pat, plane, 1.1 - prior) for pat, plane, prior
                         in diagnose.PLANE_PRIORS)
        liar = diagnose_records(recs, priors=inverted)
        assert liar["incidents"][0]["top"]["plane"] == "slo"
        assert liar["attribution_frac"] == 0.0
        ok, lines = check_gates({"incidents": liar},
                                min_attribution_frac=0.99)
        assert not ok and "FAIL" in lines[0]

    def test_no_chaos_any_suspect_counts(self):
        recs = [_rec("event/brownout_transition", 94.0, new=1),
                _rec("anomaly/serve_ttft_ms", 95.0, z=9.0)]
        doc = diagnose_records(recs)
        assert not doc["chaos_fired"]
        assert doc["attribution_frac"] == 1.0 and doc["unattributed"] == 0

    def test_chaos_off_twin_zero_anomalies_vacuous_pass(self):
        doc = diagnose_records([_rec("event/brownout_transition", 94.0)])
        assert doc["anomalies"] == 0
        assert doc["attribution_frac"] == 1.0       # vacuously attributed
        assert check_gates({"incidents": doc},
                           min_attribution_frac=0.99)[0]

    def test_unattributed_anomaly_is_counted(self):
        doc = diagnose_records([_rec("anomaly/serve_ttft_ms", 95.0)])
        assert doc["unattributed"] == 1
        # no chaos: frac reads 0/1
        assert doc["attribution_frac"] == 0.0

    def test_gate_fails_when_incidents_section_missing(self):
        ok, lines = check_gates({}, min_attribution_frac=0.5)
        assert not ok and "not measured" in lines[0]


# ---------------------------------------------------------------------------
# standing incidents (bench-ledger stall)


def _ledger_row(kind, n, error=None, stage=None, run=None):
    row = {"kind": kind, "n": n, "run": run or f"r{n:02d}"}
    if error:
        row.update(error=error, stage=stage or "preflight")
    return row


class TestLedgerStanding:
    def _write(self, tmp_path, rows):
        with open(os.path.join(tmp_path, "LEDGER.jsonl"), "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")

    def test_trailing_streak_is_standing(self, tmp_path):
        self._write(tmp_path, [
            _ledger_row("sparse", 1),
            _ledger_row("sparse", 2, error="tpu_unavailable"),
            _ledger_row("sparse", 3, error="tpu_unavailable"),
            _ledger_row("sparse", 4, error="tpu_unavailable")])
        out = ledger_standing_incidents(str(tmp_path))
        assert len(out) == 1
        st = out[0]
        assert st["kind"] == "bench_ledger_stalled"
        assert st["bench_kind"] == "sparse" and st["streak"] == 3
        assert "tpu_unavailable@preflight" in st["reasons"]
        assert "STALLED" in st["summary"]

    def test_recovered_ledger_is_not_standing(self, tmp_path):
        # errors exist but the LAST run succeeded: not stalled
        self._write(tmp_path, [
            _ledger_row("sparse", 1, error="tpu_unavailable"),
            _ledger_row("sparse", 2, error="tpu_unavailable"),
            _ledger_row("sparse", 3, error="tpu_unavailable"),
            _ledger_row("sparse", 4)])
        assert ledger_standing_incidents(str(tmp_path)) == []

    def test_short_streak_is_not_standing(self, tmp_path):
        self._write(tmp_path, [
            _ledger_row("sparse", 1),
            _ledger_row("sparse", 2, error="tpu_unavailable"),
            _ledger_row("sparse", 3, error="tpu_unavailable")])
        assert ledger_standing_incidents(str(tmp_path)) == []

    def test_ledger_found_walking_up_from_logdir(self, tmp_path):
        self._write(tmp_path, [
            _ledger_row("mlp", 1, error="oom"),
            _ledger_row("mlp", 2, error="oom"),
            _ledger_row("mlp", 3, error="oom")])
        logdir = tmp_path / "results" / "run" / "logs"
        logdir.mkdir(parents=True)
        out = ledger_standing_incidents(str(logdir))
        assert len(out) == 1 and out[0]["bench_kind"] == "mlp"

    def test_no_ledger_is_empty_never_error(self, tmp_path):
        assert ledger_standing_incidents(str(tmp_path)) == []
        assert ledger_standing_incidents(None) == []


# ---------------------------------------------------------------------------
# admin endpoint: /incidentz + the self-describing index


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
        return r.status, json.loads(r.read())


class TestAdminIncidentz:
    def test_incidentz_serves_ring_and_standing(self, tmp_path):
        with open(os.path.join(tmp_path, "LEDGER.jsonl"), "w") as f:
            for n in (1, 2, 3):
                f.write(json.dumps(_ledger_row(
                    "sparse", n, error="tpu_unavailable")) + "\n")
        diagnose.get_ring().push(
            {"anomaly": {"name": "anomaly/serve_ttft_ms"}, "top": None,
             "suspects": []})
        srv = AdminServer(0, logdir=str(tmp_path)).start()
        try:
            code, doc = _get(srv.port, "/incidentz")
            assert code == 200 and doc["total"] == 1
            assert doc["incidents"][0]["anomaly"]["name"] == \
                "anomaly/serve_ttft_ms"
            assert doc["standing"][0]["kind"] == "bench_ledger_stalled"
        finally:
            srv.close()

    def test_root_index_enumerates_all_with_armed_markers(self):
        srv = AdminServer(0).start()     # no slo/fleet/control sources
        try:
            code, idx = _get(srv.port, "/")
            assert code == 200
            eps = idx["endpoints"]
            for path in ("/statz", "/healthz", "/tracez", "/slo",
                         "/controlz", "/fleetz", "/memz", "/incidentz"):
                assert path in eps       # conditional mounts still LISTED
            assert eps["/statz"] == "armed"
            assert eps["/incidentz"] == "armed"
            assert eps["/fleetz"] == "unarmed"
            assert eps["/controlz"] == "unarmed"
        finally:
            srv.close()

    def test_unknown_path_404_with_nearest_hint(self):
        srv = AdminServer(0).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.port, "/incidents")
            assert ei.value.code == 404
            body = json.loads(ei.value.read())
            assert "/incidentz" in body["hint"]
            assert "/statz" in body["endpoints"]
        finally:
            srv.close()
