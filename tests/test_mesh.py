"""Mesh + sharding layer tests (SURVEY.md §4: sharding specs are unit-tested)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dtf_tpu.parallel import mesh as mesh_lib
from dtf_tpu.parallel import sharding as sh
from dtf_tpu.parallel.mesh import MeshSpec, make_mesh


class TestMeshSpec:
    def test_parse_single(self):
        s = MeshSpec.parse("data=-1")
        assert s.names == ("data",) and s.sizes == (-1,)

    def test_parse_multi(self):
        s = MeshSpec.parse("data=4,tensor=2")
        assert s.names == ("data", "tensor") and s.sizes == (4, 2)

    def test_resolve_infers(self):
        assert MeshSpec.parse("data=-1,tensor=2").resolve(8).sizes == (4, 2)

    def test_resolve_mismatch(self):
        with pytest.raises(ValueError):
            MeshSpec.parse("data=3").resolve(8)

    def test_unknown_axis(self):
        with pytest.raises(ValueError):
            MeshSpec.parse("bogus=2")

    def test_duplicate_axis(self):
        with pytest.raises(ValueError):
            MeshSpec.parse("data=2,data=4")

    def test_two_wildcards(self):
        with pytest.raises(ValueError):
            MeshSpec.parse("data=-1,tensor=-1")

    def test_zero_or_negative_size(self):
        with pytest.raises(ValueError):
            MeshSpec.parse("data=-1,tensor=0")
        with pytest.raises(ValueError):
            MeshSpec.parse("data=-2")


class TestMakeMesh:
    def test_1d(self, devices):
        m = make_mesh("data=-1")
        assert m.axis_names == ("data",) and m.size == 8

    def test_2d(self, devices):
        m = make_mesh("data=4,tensor=2")
        assert dict(m.shape) == {"data": 4, "tensor": 2}

    def test_subset_devices(self, devices):
        m = make_mesh("data=4", devices=devices[:4])
        assert m.size == 4


class TestShardingRules:
    def test_logical_to_spec_defaults(self):
        spec = sh.logical_to_spec(("batch", "embed"))
        assert spec == P("data", None)

    def test_unknown_logical_replicates(self):
        assert sh.logical_to_spec(("nonesuch",)) == P(None)

    def test_missing_mesh_axis_replicates(self, mesh8):
        # 'mlp' maps to 'tensor', but mesh8 has no tensor axis -> replicated.
        assert sh.logical_to_spec(("batch", "mlp"), mesh=mesh8) == P("data", None)

    def test_tensor_axis_used_when_present(self, mesh_2d):
        assert sh.logical_to_spec(("batch", "mlp"), mesh=mesh_2d) == P("data", "tensor")

    def test_batch_spec_shards_leading(self, mesh8):
        x = jnp.zeros((16, 4))
        xs = jax.device_put(x, sh.batch_spec(mesh8, x.ndim))
        assert xs.sharding.spec == P(("data",), None)
        # Each device holds 1/8 of the batch.
        assert xs.addressable_shards[0].data.shape == (2, 4)

    def test_replicate(self, mesh8):
        x = sh.replicate(mesh8, jnp.ones((3, 3)))
        assert x.sharding.is_fully_replicated

    def test_shard_batch_handles_scalars(self, mesh8):
        tree = {"x": jnp.ones((16, 4)), "step": jnp.float32(3.0)}
        out = sh.shard_batch(mesh8, tree)
        assert out["step"].sharding.is_fully_replicated
        assert out["x"].sharding.spec == P(("data",), None)

    def test_apply_rules_tree(self, mesh_2d):
        logical = {"w": ("embed", "mlp"), "b": ("mlp",)}
        shardings = sh.apply_rules(logical, mesh_2d)
        assert shardings["w"].spec == P(None, "tensor")
        assert shardings["b"].spec == P("tensor")


class TestCollectives:
    def test_all_reduce_mean(self, mesh8):
        from dtf_tpu.parallel import collectives as col

        def f(x):
            return col.all_reduce_mean(x, "data")

        g = col.shard_map_fn(f, mesh=mesh8, in_specs=P("data"),
                             out_specs=P())
        x = jnp.arange(8.0)
        np.testing.assert_allclose(g(x), 3.5)

    def test_ring_permute(self, mesh8):
        from dtf_tpu.parallel import collectives as col

        def f(x):
            return col.ring_permute(x, "data")

        g = col.shard_map_fn(f, mesh=mesh8, in_specs=P("data"),
                             out_specs=P("data"))
        out = g(jnp.arange(8.0))
        np.testing.assert_allclose(out, jnp.roll(jnp.arange(8.0), 1))

    def test_reduce_scatter(self, mesh8):
        from dtf_tpu.parallel import collectives as col

        def f(x):
            return col.reduce_scatter(x, "data", scatter_axis=0)

        g = col.shard_map_fn(f, mesh=mesh8, in_specs=P(None),
                             out_specs=P("data"))
        x = jnp.ones((8,))
        np.testing.assert_allclose(g(x), 8.0 * jnp.ones((8,)))

    def test_all_gather_tiled_concat_order(self, mesh8):
        """tiled=True semantics pinned: rank k's 2-element shard lands at
        output block [2k : 2k+2] — mesh-axis-index order, no interleave."""
        from dtf_tpu.parallel import collectives as col

        def f(shard):
            return col.all_gather(shard, "data")

        g = col.shard_map_fn(f, mesh=mesh8, in_specs=P("data"),
                             out_specs=P())
        x = jnp.arange(16.0)            # rank k holds [2k, 2k+1]
        np.testing.assert_array_equal(np.asarray(g(x)), np.arange(16.0))

    def test_reduce_scatter_shard_ownership(self, mesh8):
        """tiled=True semantics pinned: after the sum-reduce, rank k keeps
        input rows [k*m/n : (k+1)*m/n] — so reduce_scatter followed by
        all_gather is the identity on a replicated input (x N)."""
        from dtf_tpu.parallel import collectives as col

        def f(x):
            s = col.reduce_scatter(x, "data", scatter_axis=0)
            return s, col.all_gather(s, "data")

        g = col.shard_map_fn(f, mesh=mesh8, in_specs=P(None),
                             out_specs=(P("data"), P()))
        x = jnp.arange(16.0)
        shards, gathered = g(x)
        # rank k's shard (collected over the data axis) == 8 * its rows
        np.testing.assert_allclose(np.asarray(shards),
                                   8.0 * np.arange(16.0))
        np.testing.assert_allclose(np.asarray(gathered),
                                   8.0 * np.arange(16.0))

    def test_reduce_scatter_uneven_divisor_message(self, mesh8):
        """An indivisible scatter dim fails with the shape arithmetic
        spelled out (not an XLA shape-inference stack)."""
        from dtf_tpu.parallel import collectives as col

        def f(x):
            return col.reduce_scatter(x, "data", scatter_axis=0)

        g = col.shard_map_fn(f, mesh=mesh8, in_specs=P(None),
                             out_specs=P("data"))
        with pytest.raises(Exception,
                           match=r"dim 9 .*not.*divisible.*size 8"):
            g(jnp.ones((9,)))


class TestClusterBootstrap:
    def test_single_process_zero_config(self, devices):
        from dtf_tpu.cluster import bootstrap

        c = bootstrap()
        assert c.num_processes == 1
        assert c.is_coordinator
        assert c.mesh.size == 8

    def test_ps_job_name_joins_as_peer(self, devices):
        from dtf_tpu.cluster import bootstrap
        from dtf_tpu.config import ClusterConfig

        c = bootstrap(ClusterConfig(job_name="ps", mesh="data=-1"))
        assert c.mesh.size == 8  # no separate PS process

    def test_multiprocess_requires_coordinator(self):
        from dtf_tpu.cluster import bootstrap
        from dtf_tpu.config import ClusterConfig

        with pytest.raises(ValueError):
            bootstrap(ClusterConfig(num_processes=2))


class TestConfig:
    def test_reference_cli_contract(self):
        """--job_name/--task_index survive (BASELINE.json north star)."""
        from dtf_tpu.config import parse_args

        cc, tc = parse_args(["--job_name", "worker", "--task_index", "3"])
        assert cc.job_name == "worker"
        assert cc.task_index == 3
        assert cc.process_id == 3

    def test_reference_hyperparam_defaults(self):
        """Defaults match tf_distributed.py:21-23 for comparability."""
        from dtf_tpu.config import parse_args

        _, tc = parse_args([])
        assert tc.batch_size == 100
        assert tc.learning_rate == 0.0005
        assert tc.epochs == 20
        assert tc.seed == 1

    def test_bad_job_name_rejected(self):
        from dtf_tpu.config import parse_args

        with pytest.raises(ValueError):
            parse_args(["--job_name", "evaluator"])

    def test_bad_job_name_rejected_programmatically(self):
        from dtf_tpu.config import ClusterConfig

        with pytest.raises(ValueError):
            ClusterConfig(job_name="evaluator")

    def test_optional_int_flag_parses_as_int(self):
        from dtf_tpu.config import parse_args

        _, tc = parse_args(["--per_device_batch", "64"])
        assert tc.per_device_batch == 64
        assert isinstance(tc.per_device_batch, int)
