"""Serving engine (dtf_tpu/serve): paged-KV parity, scheduler
determinism, admission control, continuous-batching behavior, and the
closed-loop load generator.

The two ISSUE-level pins live here:

* **paged parity** — the paged/blocked KV cache must emit tokens
  IDENTICAL to the contiguous-cache decode path (``GPT.generate``)
  under a pinned seed, greedy and sampled, single-device and TP mesh,
  including pool layouts fragmented by request churn;
* **scheduler determinism** — the same seeded arrival trace under the
  virtual clock reproduces the same batch-composition sequence exactly
  (``engine.batch_log``), which is what makes the load bench's
  continuous-vs-static A/B a measurement instead of a lottery.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.serve import (BlockAllocator, PoolExhausted, Request,
                           Scheduler, ServingEngine, VirtualClock,
                           blocks_for, contiguous_table)
from dtf_tpu.serve.paged_kv import TRASH_BLOCK, dense_table

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    """One model object for the whole module: serve/decode.py caches
    compiled steps on the model keyed by geometry, so sharing it means
    every engine in this file reuses the same executables."""
    from dtf_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig.tiny())
    return model, model.init(jax.random.key(0))


def _mk_engine(model, params, **kw):
    kw.setdefault("clock", VirtualClock())
    kw.setdefault("num_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("blocks_per_slot", 8)
    return ServingEngine(model, params, **kw)


def _mk_trace(rng, n, *, qps=50.0, p_lens=(3, 5, 8, 12), o_lens=(3, 6, 10),
              temperature=0.0, vocab=128):
    trace, t = [], 0.0
    for rid in range(n):
        t += float(rng.exponential(1.0)) / qps
        p = int(rng.choice(p_lens))
        trace.append((t, {
            "rid": rid,
            "prompt": rng.integers(0, vocab, (p,)).astype(np.int32),
            "max_new_tokens": int(rng.choice(o_lens)),
            "temperature": temperature,
        }))
    return trace


# ---------------------------------------------------------------------------
# allocator + tables (pure Python, no jax)
# ---------------------------------------------------------------------------


class TestBlockAllocator:
    def test_lowest_id_first_and_canonical_reuse(self):
        a = BlockAllocator(8)                      # usable ids 1..7
        assert a.allocate(3) == [1, 2, 3]
        assert a.allocate(2) == [4, 5]
        a.free([2, 4])
        # freed ids come back sorted: same schedule -> same layout
        assert a.allocate(3) == [2, 4, 6]
        assert a.used_blocks == 6 and a.free_blocks == 1

    def test_exhaustion_is_backpressure_not_crash(self):
        a = BlockAllocator(4)
        a.allocate(2)
        assert not a.can_allocate(2)
        with pytest.raises(PoolExhausted):
            a.allocate(2)
        assert a.free_blocks == 1                  # failed alloc took nothing

    def test_free_validation(self):
        a = BlockAllocator(4)
        got = a.allocate(2)
        with pytest.raises(ValueError, match="double free"):
            a.free(got + got[:1])
        with pytest.raises(ValueError, match="outside"):
            a.free([TRASH_BLOCK])
        with pytest.raises(ValueError, match="outside"):
            a.free([99])
        with pytest.raises(ValueError, match=">= 2"):
            BlockAllocator(1)

    def test_blocks_for(self):
        assert blocks_for(0, 4) == 0
        assert blocks_for(1, 4) == 1
        assert blocks_for(4, 4) == 1
        assert blocks_for(5, 4) == 2


class TestTables:
    def test_dense_table_padding_and_overflow(self):
        t = dense_table([None, [3, 5], [2]], 3)
        np.testing.assert_array_equal(
            t, [[-1, -1, -1], [3, 5, -1], [2, -1, -1]])
        with pytest.raises(ValueError, match="window"):
            dense_table([[1, 2, 3, 4]], 3)

    def test_contiguous_table_is_identity_layout(self):
        t = contiguous_table(3, 4)
        np.testing.assert_array_equal(
            t, [[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]])
        assert TRASH_BLOCK not in t


# ---------------------------------------------------------------------------
# scheduler policy (jax-free)
# ---------------------------------------------------------------------------


def _req(rid, p_len=4, max_new=4, t=0.0):
    return Request(rid=rid, prompt=np.zeros((p_len,), np.int32),
                   max_new_tokens=max_new, arrival_s=t)


def _sched(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("blocks_per_slot", 4)
    kw.setdefault("allocator",
                  BlockAllocator(1 + kw["num_slots"] * kw["blocks_per_slot"]))
    return Scheduler(**kw)


class TestScheduler:
    def test_continuous_refills_on_release(self):
        s = _sched()
        for i in range(3):
            assert s.submit(_req(i), 0.0) == "queued"
        got = s.admit(0.0)
        assert [r.rid for _, r in got] == [0, 1]
        assert s.admit(0.0) == []                 # slots full
        s.release(got[0][1])
        got2 = s.admit(0.0)
        assert [r.rid for _, r in got2] == [2]    # same-iteration refill
        assert got2[0][0] == got[0][0]            # reuses the freed slot

    def test_admission_rejections(self):
        s = _sched(max_queue=1)
        assert s.submit(_req(0, p_len=14, max_new=4), 0.0) == \
            "rejected_too_long"                   # 18 > window 16
        assert s.submit(_req(1, max_new=0), 0.0) == "rejected_empty"
        assert s.submit(_req(2), 0.0) == "queued"
        assert s.submit(_req(3), 0.0) == "rejected_queue_full"

    def test_cancel_with_duplicate_rid_in_queue_is_identity_based(self):
        """Two LIVE Request objects may share a rid (a fleet acceptor's
        failover/hedge resubmits a rid while the original copy still
        sits queued on the old replica).  cancel() must tear out the
        OBJECT it was handed — field equality on such a pair walks into
        the numpy prompt and raised "truth value of an array is
        ambiguous", crashing the engine driver (found by a fleet chaos
        drive; Request is eq=False now)."""
        s = _sched()
        queued = _req(7)
        twin = _req(7)                   # same rid, same-shape prompt
        assert s.submit(queued, 0.0) == "queued"
        assert queued != twin            # identity eq, not field eq
        assert s.cancel(twin) == "gone"  # must not touch the queued copy
        assert list(s.queue) == [queued]
        assert s.cancel(queued, status="cancelled") == "queued"
        assert not s.queue and queued.status == "cancelled"

    def test_worst_case_block_reservation(self):
        s = _sched()
        # prompt 5 pads to 8 rows (2 blocks); decode writes rows 5..7
        # land inside the padding, so 2 blocks cover prompt+4 new tokens
        assert s._blocks_needed(_req(0, p_len=5, max_new=4)) == 2
        # 6 new tokens write rows 5..9 -> 3 blocks
        assert s._blocks_needed(_req(0, p_len=5, max_new=6)) == 3

    def test_request_larger_than_pool_rejected_not_wedged(self):
        """A request needing more blocks than the WHOLE pool holds must
        be rejected at submit — queued, it could never be admitted
        (nothing in flight can free enough) and would head-of-line
        block everything behind it forever."""
        s = _sched(num_slots=2, blocks_per_slot=8,
                   allocator=BlockAllocator(5))     # 4 usable blocks
        big = _req(0, p_len=14, max_new=8)          # needs 6 blocks <= window
        assert s._blocks_needed(big) <= s.blocks_per_slot
        assert s.submit(big, 0.0) == "rejected_too_long"
        assert s.submit(_req(1, p_len=4, max_new=4), 0.0) == "queued"
        assert [r.rid for _, r in s.admit(0.0)] == [1]

    def test_reservation_makes_midflight_exhaustion_impossible(self):
        # pool of 3 usable blocks, requests need 2 each: second stays
        # QUEUED (not admitted then crashed) until the first releases
        s = _sched(num_slots=2, allocator=BlockAllocator(4))
        s.submit(_req(0, p_len=5, max_new=4), 0.0)
        s.submit(_req(1, p_len=5, max_new=4), 0.0)
        got = s.admit(0.0)
        assert [r.rid for _, r in got] == [0]
        assert len(s.queue) == 1
        s.release(got[0][1])
        assert [r.rid for _, r in s.admit(0.0)] == [1]

    def test_prefill_budget_drips_long_prompts(self):
        # budget = one 16-token window; three 16-token prompts arrive at
        # once -> one prefill per admit call (the first always goes
        # through), so in-flight decodes never stall behind a wave
        s = _sched(num_slots=3, prefill_token_budget=16,
                   allocator=BlockAllocator(64))
        for i in range(3):
            s.submit(_req(i, p_len=12, max_new=4), 0.0)
        assert len(s.admit(0.0)) == 1
        assert len(s.admit(0.0)) == 1
        assert len(s.admit(0.0)) == 1

    def test_static_mode_fill_or_timeout(self):
        s = _sched(num_slots=3, mode="static", static_batch_wait_s=0.05)
        s.submit(_req(0), 0.0)
        s.submit(_req(1), 0.01)
        assert s.admit(0.02) == []                # not full, not aged
        got = s.admit(0.05)                       # aged out: batch forms
        assert [r.rid for _, r in got] == [0, 1]
        s.submit(_req(2), 0.06)
        assert s.admit(1.0) == []                 # batch still active
        for _, r in got:
            s.release(r)
        assert [r.rid for _, r in s.admit(1.0)] == [2]

    def test_static_full_batch_goes_immediately(self):
        s = _sched(num_slots=2, mode="static", static_batch_wait_s=99.0)
        s.submit(_req(0), 0.0)
        s.submit(_req(1), 0.0)
        assert len(s.admit(0.0)) == 2             # full: no wait

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="mode"):
            _sched(mode="bursty")


# ---------------------------------------------------------------------------
# paged-KV parity (the ISSUE pin)
# ---------------------------------------------------------------------------


class TestPagedParity:
    """Paged decode == contiguous decode, token for token.  The solo
    reference (one request, fresh engine, blocks 1..n in order) IS the
    identity block table — the contiguous per-slot cache; the shared
    engines run permuted/fragmented tables over a churning pool."""

    def _reference_greedy(self, model, params, prompts, new):
        outs = []
        for p, n in zip(prompts, new):
            out = model.generate(params, jnp.asarray(p)[None], n,
                                 temperature=0.0)
            outs.append(np.asarray(out)[0, len(p):].tolist())
        return outs

    def test_greedy_matches_contiguous_generate(self, tiny_model):
        """4 requests churn through 3 slots of a shared 18-block pool:
        the block tables fragment (freed blocks are reused out of
        order), yet every request's tokens equal the contiguous-cache
        ``GPT.generate`` run."""
        model, params = tiny_model
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 128, (n,)).astype(np.int32)
                   for n in (5, 8, 3, 12)]
        new = [10, 6, 12, 7]
        refs = self._reference_greedy(model, params, prompts, new)
        eng = _mk_engine(model, params, num_blocks=1 + 3 * 6,
                         blocks_per_slot=6)
        res = eng.run([(0.01 * i, dict(rid=i, prompt=p, max_new_tokens=n))
                       for i, (p, n) in enumerate(zip(prompts, new))])
        for i in range(4):
            assert res[i].tokens == refs[i], f"request {i} diverged"

    def test_greedy_tp_mesh_matches_single(self, tiny_model, mesh_2d):
        """TP-sharded params through the paged engine: GSPMD inserts the
        collectives, the tokens must not change (the serving-side analog
        of test_gpt's TestShardedDecode)."""
        from dtf_tpu.parallel import sharding as sh
        from jax.sharding import NamedSharding, PartitionSpec as P
        model, params = tiny_model
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, 128, (n,)).astype(np.int32)
                   for n in (4, 9)]
        new = [8, 8]
        refs = self._reference_greedy(model, params, prompts, new)
        sp = jax.device_put(params,
                            sh.apply_rules(model.axes(), mesh_2d))
        eng = _mk_engine(model, sp, num_slots=2)
        res = eng.run([(0.0, dict(rid=i, prompt=p, max_new_tokens=n))
                       for i, (p, n) in enumerate(zip(prompts, new))])
        for i in range(2):
            assert res[i].tokens == refs[i], f"request {i} diverged on TP"

    def test_sampled_pinned_seed_composition_independent(self, tiny_model):
        """temperature=1.0 under a pinned engine seed: a request's draws
        come from its own (seed, rid) stream, so solo (= identity/
        contiguous table), continuous (fragmented shared pool), and
        static batching all emit IDENTICAL tokens."""
        model, params = tiny_model
        rng = np.random.default_rng(11)
        trace = _mk_trace(rng, 5, temperature=1.0)

        def run(mode, solo_rid=None):
            eng = _mk_engine(model, params, mode=mode, seed=42,
                             num_blocks=1 + 3 * 8)
            t = (trace if solo_rid is None else
                 [(0.0, kw) for _, kw in trace if kw["rid"] == solo_rid])
            return {r.rid: r.tokens for r in eng.run(t).values()
                    if r.status == "completed"}

        cont = run("continuous")
        stat = run("static")
        solo = {}
        for rid in cont:
            solo.update(run("continuous", solo_rid=rid))
        assert cont == stat, "continuous vs static tokens diverged"
        assert cont == solo, "shared-pool vs solo tokens diverged"

    def test_pool_fully_recycled_after_drain(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params, num_blocks=1 + 3 * 8)
        rng = np.random.default_rng(5)
        eng.run(_mk_trace(rng, 6))
        assert eng.scheduler.allocator.used_blocks == 0
        assert eng._blocks_peak > 0
        assert eng.scheduler.allocator.allocate(1) == [1]  # canonical again


# ---------------------------------------------------------------------------
# scheduler determinism (the other ISSUE pin)
# ---------------------------------------------------------------------------


class TestSchedulerDeterminism:
    def test_same_trace_same_batch_compositions(self, tiny_model):
        model, params = tiny_model

        def run():
            eng = _mk_engine(model, params, seed=7,
                             num_blocks=1 + 3 * 8)
            eng.run(_mk_trace(np.random.default_rng(13), 8, qps=30.0))
            return eng.batch_log

        log_a, log_b = run(), run()
        assert log_a == log_b
        assert any(e[0] == "decode" for e in log_a)

    def test_continuous_batching_actually_joins_in_flight(self, tiny_model):
        """The whole point: decode batch composition must CHANGE while
        earlier members are still in flight (a joined request decodes
        next to one admitted earlier)."""
        model, params = tiny_model
        eng = _mk_engine(model, params, num_blocks=1 + 3 * 8)
        eng.run(_mk_trace(np.random.default_rng(17), 8, qps=25.0,
                          o_lens=(4, 16)))
        decodes = [set(e[1]) for e in eng.batch_log if e[0] == "decode"]
        joined = any(b - a and b & a
                     for a, b in zip(decodes, decodes[1:]))
        assert joined, "no decode batch gained a member mid-flight"

    def test_static_never_mixes_generations(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params, mode="static",
                         num_blocks=1 + 3 * 8)
        eng.run(_mk_trace(np.random.default_rng(19), 6, qps=25.0))
        decodes = [set(e[1]) for e in eng.batch_log if e[0] == "decode"]
        for a, b in zip(decodes, decodes[1:]):
            assert not (b - a) or not (b & a), \
                "static batch admitted mid-flight"


# ---------------------------------------------------------------------------
# engine behavior
# ---------------------------------------------------------------------------


class TestEngineBehavior:
    def test_streaming_tokens_arrive_in_order(self, tiny_model):
        model, params = tiny_model
        seen = []
        eng = _mk_engine(model, params,
                         on_token=lambda r, t, d: seen.append(
                             (r.rid, t, d)))
        res = eng.run(_mk_trace(np.random.default_rng(23), 3))
        for rid, req in res.items():
            stream = [(t, d) for r, t, d in seen if r == rid]
            assert [t for t, _ in stream] == req.tokens
            assert [d for _, d in stream] == \
                [False] * (len(stream) - 1) + [True]

    def test_eos_stops_early_and_frees_blocks(self, tiny_model):
        """Deterministic EOS: pick the greedy path's 3rd token as the
        eos id — the engine must stop there (3 tokens, not max_new)."""
        model, params = tiny_model
        rng = np.random.default_rng(29)
        prompt = rng.integers(0, 128, (6,)).astype(np.int32)
        ref = np.asarray(model.generate(
            params, jnp.asarray(prompt)[None], 10,
            temperature=0.0))[0, 6:].tolist()
        eos = ref[2]
        eng = _mk_engine(model, params)
        res = eng.run([(0.0, dict(rid=0, prompt=prompt,
                                  max_new_tokens=10, eos_id=eos))])
        assert res[0].tokens == ref[:3]
        assert res[0].tokens[-1] == eos
        assert eng.scheduler.allocator.used_blocks == 0

    def test_rejected_requests_land_in_results(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params, max_queue=64)
        req = eng.submit(np.zeros((40,), np.int32), 40)  # > window 32
        assert req.status == "rejected"
        assert eng.results[req.rid] is req
        assert eng.summary()["rejected"] == 1

    def test_tiny_pool_defers_but_completes_all(self, tiny_model):
        """Pool sharing under pressure: 8 requests through a pool that
        holds ~2 windows — admissions wait for blocks, nothing crashes,
        everything completes, and peak usage respects the pool."""
        model, params = tiny_model
        eng = _mk_engine(model, params, num_blocks=9)   # 8 usable blocks
        res = eng.run(_mk_trace(np.random.default_rng(31), 8, qps=100.0))
        assert sum(r.status == "completed" for r in res.values()) == 8
        assert eng._blocks_peak <= 8

    def test_summary_latency_and_goodput(self, tiny_model):
        import dtf_tpu.telemetry as tel
        model, params = tiny_model
        tel.reset()
        eng = _mk_engine(model, params)
        eng.run(_mk_trace(np.random.default_rng(37), 5, qps=40.0))
        s = eng.summary(slo_ttft_ms=1e6)
        assert s["completed"] == 5
        assert s["ttft_ms_p50"] <= s["ttft_ms_p99"]
        assert s["tpot_ms_p50"] > 0
        assert s["goodput_qps"] == pytest.approx(s["completed_qps"])
        assert s["slo_attainment"] == 1.0
        # an impossible SLO zeroes goodput but not completion
        s2 = eng.summary(slo_ttft_ms=0.0)
        assert s2["goodput_qps"] == 0.0 and s2["completed"] == 5
        h = tel.histogram("serve/ttft_ms")
        assert h.count == 5 and h.min >= 0.0

    def test_write_telemetry_report_renders_serving(self, tiny_model,
                                                    tmp_path):
        from dtf_tpu.telemetry import report as rep
        import dtf_tpu.telemetry as tel
        model, params = tiny_model
        tel.reset()
        eng = _mk_engine(model, params)
        eng.run(_mk_trace(np.random.default_rng(41), 4))
        path = eng.write_telemetry(str(tmp_path), slo_ttft_ms=500.0)
        doc = json.load(open(path))
        assert doc["serving"]["completed"] == 4
        text = rep.render(rep.build_report(str(tmp_path)))
        assert "Serving (SLO / goodput)" in text
        assert "goodput_qps" in text and "serve/requests_completed" in text

    def test_flash_block_size_guard(self):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        model = GPT(GPTConfig.tiny(use_flash=True))
        with pytest.raises(ValueError, match="multiple of 8"):
            ServingEngine(model, None, block_size=4)


# ---------------------------------------------------------------------------
# closed-loop load generator
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestServeCLI:
    """``python -m dtf_tpu.serve`` end to end, in-process (each call
    builds a fresh model, so these carry the slow marker; the full-suite
    serve lane drives the same paths from the shell)."""

    def test_demo_completes_and_reports(self, capsys):
        from dtf_tpu.serve.__main__ import main
        rc = main(["--preset", "tiny", "--demo", "5", "--qps", "20",
                   "--clock", "virtual", "--seed", "1"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["completed"] == 5
        assert summary["completed_all_attempts"] == 5
        assert summary["ttft_ms_p99"] >= summary["ttft_ms_p50"] >= 0

    def test_wedge_supervisor_restart_replays(self, tmp_path, capsys):
        """Resilience spine reuse: a server wedged at iteration 2 of
        attempt 0 restarts under the supervisor and REPLAYS the
        unfinished requests; health beats land in --health_dir."""
        import os
        from dtf_tpu.serve.__main__ import main
        hdir = str(tmp_path / "health")
        rc = main(["--preset", "tiny", "--demo", "4", "--qps", "50",
                   "--clock", "virtual", "--seed", "2",
                   "--wedge_at", "2", "--max_restarts", "1",
                   "--health_dir", hdir])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["completed_all_attempts"] == 4
        beat = os.path.join(hdir, "hb_0")
        assert os.path.exists(beat)
        assert int(open(beat).read().split()[0]) > 0

    def test_wedge_without_restart_budget_fails(self, capsys):
        from dtf_tpu.resilience.supervisor import SupervisorGaveUp
        from dtf_tpu.serve.__main__ import main
        with pytest.raises(SupervisorGaveUp):
            main(["--preset", "tiny", "--demo", "4", "--qps", "50",
                  "--clock", "virtual", "--wedge_at", "1",
                  "--max_restarts", "0"])


class TestLoadGen:
    def test_poisson_trace_seeded_and_rate_scaled(self):
        from dtf_tpu.bench.serve_load import poisson_trace
        kw = dict(seed=5, n_requests=12, prompt_lens=[4, 8],
                  output_lens=[2, 6], vocab_size=128)
        a = poisson_trace(qps=4.0, **kw)
        b = poisson_trace(qps=4.0, **kw)
        fast = poisson_trace(qps=8.0, **kw)
        assert [t for t, _ in a] == [t for t, _ in b]
        for (ta, kwa), (tf, kwf) in zip(a, fast):
            assert tf == pytest.approx(ta / 2.0)   # unit-rate chain
            np.testing.assert_array_equal(kwa["prompt"], kwf["prompt"])

    def test_sustained_goodput_selection(self):
        from dtf_tpu.bench.serve_load import sustained_goodput
        pts = [{"offered_qps": 4, "ttft_ms_p99": 50, "goodput_qps": 3.5},
               {"offered_qps": 8, "ttft_ms_p99": 90, "goodput_qps": 7.0},
               {"offered_qps": 16, "ttft_ms_p99": 900, "goodput_qps": 9.0}]
        out = sustained_goodput(pts, budget_ms=100.0)
        assert out["sustained_goodput_qps"] == 7.0
        assert out["at_offered_qps"] == 8
        none = sustained_goodput(pts, budget_ms=10.0)
        assert none["sustained_goodput_qps"] == 0.0
        assert none["at_offered_qps"] is None

    def test_check_needs_both_modes(self):
        from dtf_tpu.bench import serve_load
        with pytest.raises(SystemExit):
            serve_load.main(["--check", "--mode", "continuous"])

    def test_ab_continuous_beats_static_on_goodput(self, tiny_model):
        """The acceptance bar, in-process on the virtual clock: at the
        same p99 TTFT budget, continuous batching sustains >= 1.5x the
        static baseline's goodput QPS (deterministic — the cost model
        and trace are seeded)."""
        import argparse
        from dtf_tpu.bench.serve_load import AB_MIN_RATIO, sweep
        model, params = tiny_model
        ns = argparse.Namespace(
            mode="both", qps_list=[8.0, 20.0], requests=32,
            prompt_lens_list=[4, 8, 16], output_lens_list=[2, 8, 32],
            temperature=0.0, top_k=0, top_p=1.0, slots=4, block_size=16,
            pool_blocks=None, max_queue=256, slo_ttft_ms=300.0,
            clock="virtual", seed=0)
        out = sweep(model, params, ns)
        ab = out["ab"]
        assert ab["ratio"] >= AB_MIN_RATIO, ab
        # the curve exists: every point carries the percentile fields
        for pt in out["points"]:
            assert {"ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50",
                    "offered_qps"} <= set(pt)
