"""LLaMA-family options on the GPT model (RoPE + GQA + SwiGLU): rotation
math, causality, KV-cache decode consistency with the parallel forward,
cache-size reduction, and a train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.models.gpt import GPT, GPTConfig
from dtf_tpu.nn.rope import apply_rope


def llama_tiny(**kw):
    d = dict(rope=True, num_kv_heads=2, mlp_act="swiglu")
    d.update(kw)
    return GPTConfig.tiny(**d)


class TestRope:
    def test_preserves_norm(self):
        """Rotation is orthogonal: per-pair vector norms are unchanged."""
        x = jax.random.normal(jax.random.key(0), (2, 16, 4, 8))
        y = apply_rope(x, jnp.arange(16))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1), rtol=1e-5)

    def test_position_zero_is_identity(self):
        x = jax.random.normal(jax.random.key(1), (1, 1, 2, 8))
        np.testing.assert_allclose(apply_rope(x, jnp.zeros((1,), jnp.int32)),
                                   x, atol=1e-6)

    def test_relative_property(self):
        """<rope(q,m), rope(k,n)> depends only on m-n: shifting both
        positions by a constant leaves the dot product unchanged."""
        q = jax.random.normal(jax.random.key(2), (1, 1, 1, 16))
        k = jax.random.normal(jax.random.key(3), (1, 1, 1, 16))

        def dot(m, n, shift):
            qm = apply_rope(q, jnp.asarray([m + shift]))
            kn = apply_rope(k, jnp.asarray([n + shift]))
            return float(jnp.sum(qm * kn))

        assert dot(7, 3, 0) == pytest.approx(dot(7, 3, 11), abs=1e-4)
        assert dot(7, 3, 0) != pytest.approx(dot(8, 3, 0), abs=1e-4)

    def test_odd_head_dim_raises(self):
        with pytest.raises(ValueError, match="even"):
            apply_rope(jnp.zeros((1, 2, 1, 7)), jnp.arange(2))


class TestLlamaStyleModel:
    @pytest.fixture(scope="class")
    def model(self):
        return GPT(llama_tiny())

    @pytest.fixture(scope="class")
    def params(self, model):
        return model.init(jax.random.key(0))

    def test_no_position_table(self, params):
        assert "pos" not in params

    def test_causality(self, model, params):
        rng = np.random.default_rng(0)
        a = rng.integers(0, 128, (1, 16)).astype(np.int32)
        b = a.copy()
        b[0, 10:] = rng.integers(0, 128, 6)
        la = model.apply(params, jnp.asarray(a))
        lb = model.apply(params, jnp.asarray(b))
        np.testing.assert_allclose(la[0, :10], lb[0, :10], atol=1e-5)
        assert not np.allclose(la[0, 10:], lb[0, 10:])

    def test_gqa_cache_is_smaller(self, model):
        cache = model.init_cache(2)
        # 2 KV heads instead of 4: half the MHA cache
        assert cache["k"].shape[3] == 2
        mha_cache = GPT(GPTConfig.tiny()).init_cache(2)
        assert cache["k"].size == mha_cache["k"].size // 2

    def test_greedy_decode_matches_parallel_forward(self, model, params):
        """The KV-cache decode path (grouped attention + RoPE at dynamic
        positions) must reproduce the parallel forward's argmax."""
        prompt = jnp.asarray(
            np.random.default_rng(3).integers(0, 128, (2, 10)), jnp.int32)
        out = model.generate(params, prompt, max_new_tokens=6,
                             temperature=0.0)
        assert out.shape == (2, 16)
        np.testing.assert_array_equal(out[:, :10], prompt)
        for t in range(10, 16):
            logits = model.apply(params, out[:, :t])
            np.testing.assert_array_equal(
                np.asarray(jnp.argmax(logits[:, -1], -1), np.int32),
                np.asarray(out[:, t]))

    def test_trains(self, model, mesh8):
        from dtf_tpu import optim
        from dtf_tpu.data.datasets import synthetic_text
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        opt = optim.adam(1e-3)
        state = init_state(model, opt, seed=0, mesh=mesh8)
        step = make_train_step(model.loss, opt, mesh8, donate=False)
        toks = synthetic_text(16, 32, 128, seed=1)
        losses = []
        for i in range(6):
            state, m = step(state, put_global_batch(mesh8, toks),
                            jax.random.key(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]

    def test_swiglu_param_shapes(self, model, params):
        # gate and up are separate column-parallel projections (TP-local
        # elementwise product), each (dim, mlp_dim)
        assert params["layers"]["fc1"]["w"].shape == (2, 32, 64)
        assert params["layers"]["fc_gate"]["w"].shape == (2, 32, 64)
        assert params["layers"]["fc2"]["w"].shape == (2, 64, 32)

    def test_tensor_parallel_train_step(self):
        """The llama-style block under a data x tensor mesh: one sharded
        train step (gate/up column-parallel, GQA heads sharded)."""
        from dtf_tpu import optim
        from dtf_tpu.data.datasets import synthetic_text
        from dtf_tpu.parallel import sharding as sh
        from dtf_tpu.parallel.mesh import make_mesh
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        mesh = make_mesh("data=4,tensor=2")
        model = GPT(llama_tiny())
        shardings = sh.apply_rules(model.axes(), mesh)
        opt = optim.adam(1e-3)
        state = init_state(model, opt, seed=0, mesh=mesh,
                           param_shardings=shardings)
        step = make_train_step(model.loss, opt, mesh, donate=False)
        toks = synthetic_text(8, 32, 128, seed=2)
        state, m = step(state, put_global_batch(mesh, toks),
                        jax.random.key(0))
        assert np.isfinite(float(m["loss"]))
        assert "tensor" in str(state["params"]["layers"]["fc_gate"]["w"]
                               .sharding.spec)

    def test_remat_matches(self):
        ma = GPT(llama_tiny())
        mb = GPT(llama_tiny(remat=True))
        params = ma.init(jax.random.key(1))
        toks = jnp.asarray(
            np.random.default_rng(2).integers(0, 128, (2, 16)), jnp.int32)
        la, _ = ma.loss(params, toks)
        lb, _ = mb.loss(params, toks)
        assert float(la) == pytest.approx(float(lb), abs=1e-6)


class TestLabelSmoothing:
    def test_smoothed_loss_matches_algebraic_identity(self):
        """smoothed = (1-eps)*NLL + eps*mean(-logp) exactly; eps=0 is the
        identity (structural check — the sign of the eps-delta is data
        dependent for an untrained model, so no inequality assertions)."""
        import numpy as _np
        eps = 0.1
        toks = jnp.asarray(
            _np.random.default_rng(0).integers(0, 128, (2, 16)), jnp.int32)
        base = GPT(GPTConfig.tiny())
        smooth = GPT(GPTConfig.tiny(label_smoothing=eps))
        params = base.init(jax.random.key(0))
        l0, aux0 = base.loss(params, toks)
        le, auxe = smooth.loss(params, toks)
        logits = base.apply(params, toks)[:, :-1]
        logp = jax.nn.log_softmax(logits, axis=-1)
        uniform_term = float(-jnp.mean(logp))
        expected = (1 - eps) * float(l0) + eps * uniform_term
        assert float(le) == pytest.approx(expected, rel=1e-6)
        # perplexity reports the TRUE NLL either way (comparable runs)
        assert float(auxe["perplexity"]) == pytest.approx(
            float(aux0["perplexity"]), rel=1e-6)

    def test_invalid_eps_rejected(self):
        model = GPT(GPTConfig.tiny(label_smoothing=1.5))
        params = model.init(jax.random.key(0))
        toks = jnp.zeros((1, 8), jnp.int32)
        with pytest.raises(ValueError, match="label_smoothing"):
            model.loss(params, toks)

    def test_t5_smoothing_respects_pad_mask(self):
        from dtf_tpu.models.t5 import T5, T5Config
        import numpy as _np
        model = T5(T5Config.tiny(label_smoothing=0.1))
        params = model.init(jax.random.key(0))
        src = jnp.asarray(_np.random.default_rng(1).integers(2, 64, (2, 10)),
                          jnp.int32)
        tgt = _np.random.default_rng(2).integers(2, 64, (2, 8)).astype(
            _np.int32)
        tgt[:, 6:] = 0
        l, _ = model.loss(params, {"src": src, "tgt": jnp.asarray(tgt)})
        assert np.isfinite(float(l))
