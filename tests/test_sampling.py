"""Sampling strategies (nn/sampling.py): filter exactness, distribution
restrictions (forbidden tokens never sampled), greedy short-circuit, and
the GPT.generate integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.nn.sampling import NEG_INF, sample_token, top_k_filter, top_p_filter


def logits_row(vals):
    return jnp.asarray([vals], jnp.float32)


class TestTopK:
    def test_keeps_exactly_k(self):
        out = top_k_filter(logits_row([1.0, 4.0, 2.0, 3.0]), 2)
        np.testing.assert_array_equal(
            out[0], [NEG_INF, 4.0, NEG_INF, 3.0])

    def test_noop_for_k_zero_or_full(self):
        l = logits_row([1.0, 2.0, 3.0])
        np.testing.assert_array_equal(top_k_filter(l, 0), l)
        np.testing.assert_array_equal(top_k_filter(l, 3), l)
        np.testing.assert_array_equal(top_k_filter(l, 99), l)

    def test_per_row_independent(self):
        l = jnp.asarray([[5.0, 1.0, 0.0], [0.0, 1.0, 5.0]], jnp.float32)
        out = top_k_filter(l, 1)
        assert out[0, 0] == 5.0 and out[0, 1] == NEG_INF
        assert out[1, 2] == 5.0 and out[1, 0] == NEG_INF


class TestTopP:
    def test_keeps_nucleus(self):
        # probs ~ [0.643, 0.237, 0.087, 0.032]: p=0.7 keeps the first two
        # (the crossing token is included).
        l = logits_row([4.0, 3.0, 2.0, 1.0])
        out = top_p_filter(l, 0.7)
        np.testing.assert_array_equal(
            out[0], [4.0, 3.0, NEG_INF, NEG_INF])

    def test_always_keeps_argmax(self):
        out = top_p_filter(logits_row([10.0, 0.0, 0.0]), 1e-6)
        assert out[0, 0] == 10.0
        assert out[0, 1] == NEG_INF and out[0, 2] == NEG_INF

    def test_noop_for_p_one(self):
        l = logits_row([1.0, 2.0, 3.0])
        np.testing.assert_array_equal(top_p_filter(l, 1.0), l)

    def test_p_zero_degrades_to_greedy_not_all_masked(self):
        """p <= 0 must keep the argmax (an all-masked row would make
        categorical degenerate to always-token-0)."""
        l = logits_row([0.0, 7.0, 1.0])
        out = top_p_filter(l, 0.0)
        assert out[0, 1] == 7.0
        assert out[0, 0] == NEG_INF and out[0, 2] == NEG_INF
        samples = {int(sample_token(jax.random.key(i), l, temperature=1.0,
                                    top_p=0.0)[0]) for i in range(10)}
        assert samples == {1}


class TestSampleToken:
    def test_greedy(self):
        l = jnp.asarray([[0.0, 5.0, 1.0], [9.0, 0.0, 1.0]], jnp.float32)
        out = sample_token(jax.random.key(0), l, temperature=0.0)
        np.testing.assert_array_equal(out, [1, 0])
        assert out.dtype == jnp.int32

    def test_filtered_tokens_never_sampled(self):
        l = jnp.tile(logits_row([3.0, 2.9, -1.0, -2.0]), (64, 1))
        keys = jax.random.split(jax.random.key(1), 50)
        for k in keys[:10]:
            out = sample_token(k, l, temperature=1.0, top_k=2)
            assert set(np.asarray(out)) <= {0, 1}
        for k in keys[10:20]:
            out = sample_token(k, l, temperature=1.0, top_p=0.5)
            assert set(np.asarray(out)) <= {0, 1}   # 0.5 mass => top-2

    def test_high_temperature_flattens(self):
        """With T>>1 the sampled distribution approaches uniform; with T<<1
        it concentrates on the argmax."""
        l = jnp.tile(logits_row([2.0, 1.0, 0.0, -1.0]), (512, 1))
        hot = sample_token(jax.random.key(2), l, temperature=100.0)
        cold = sample_token(jax.random.key(2), l, temperature=0.01)
        assert len(set(np.asarray(hot))) == 4        # all tokens appear
        assert set(np.asarray(cold)) == {0}          # argmax only

    def test_fused_filter_equals_sequential_filters(self):
        """filter_logits (one sort) must match top_k_filter then
        top_p_filter (the standard composition, nucleus renormalized
        within the top-k).  Continuous fixed-seed logits: thresholds are
        deterministically far from any cumsum boundary on the test
        backend."""
        from dtf_tpu.nn.sampling import filter_logits
        l = jax.random.normal(jax.random.key(7), (8, 64), jnp.float32) * 3
        for k, p in [(8, 0.9), (0, 0.5), (5, 1.0), (3, 0.2), (64, 0.7),
                     (1, 0.99), (0, 1.0)]:
            seq = top_p_filter(top_k_filter(l, k), p)
            fused = filter_logits(l, top_k=k, top_p=p)
            np.testing.assert_array_equal(np.asarray(fused), np.asarray(seq),
                                          err_msg=f"k={k} p={p}")

    def test_fused_filter_handles_boundary_ties(self):
        """top_k_filter keeps value-ties with the kth logit; the fused
        nucleus renormalizer must include them (logits [3,2,2,0], k=2:
        three survivors, so p=0.73 keeps [3,2,2] — a k-sized mass would
        wrongly cut both 2s)."""
        from dtf_tpu.nn.sampling import filter_logits
        l = logits_row([3.0, 2.0, 2.0, 0.0])
        for p in (0.73, 0.5, 0.95, 0.2):
            seq = top_p_filter(top_k_filter(l, 2), p)
            fused = filter_logits(l, top_k=2, top_p=p)
            np.testing.assert_array_equal(np.asarray(fused), np.asarray(seq),
                                          err_msg=f"p={p}")
        out = filter_logits(l, top_k=2, top_p=0.73)
        np.testing.assert_array_equal(out[0], [3.0, 2.0, 2.0, NEG_INF])

    def test_jit_compatible(self):
        l = jnp.tile(logits_row([1.0, 2.0, 3.0, 4.0]), (4, 1))
        f = jax.jit(lambda k, l: sample_token(k, l, temperature=0.8,
                                              top_k=3, top_p=0.9))
        out = f(jax.random.key(3), l)
        assert out.shape == (4,)
        assert set(np.asarray(out)) <= {1, 2, 3}     # token 0 cut by top_k/p


class TestGenerateIntegration:
    @pytest.mark.parametrize("kw", [
        {"temperature": 0.0},
        {"temperature": 0.9, "top_k": 8},
        {"temperature": 0.9, "top_p": 0.9},
    ])
    def test_gpt_generate_with_sampling(self, kw):
        from dtf_tpu.models.gpt import GPT, GPTConfig
        cfg = GPTConfig.tiny()
        model = GPT(cfg)
        params = model.init(jax.random.key(0))
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 4)),
            jnp.int32)
        out = model.generate(params, prompt, 6, rng=jax.random.key(1), **kw)
        assert out.shape == (2, 10)
        np.testing.assert_array_equal(out[:, :4], prompt)  # prompt preserved
        assert ((0 <= out) & (out < cfg.vocab_size)).all()
