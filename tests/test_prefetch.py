"""Async device-prefetch input pipeline + AOT/persistent-compile tests.

The contracts under test (data/prefetch.py, train/compile_cache.py,
Trainer integration):

* exact trajectory — the loss sequence is BITWISE-identical between
  ``--prefetch 0`` (serial fetch->put->dispatch) and ``--prefetch 2``
  (background producer), single-process and in the simulated
  multi-process (ProcessShard) configuration;
* producer errors surface on the main thread at the step that would have
  consumed the failed batch, not earlier and not from the wrong thread;
* chaos ``loader_error@S`` / ``nan_grad@S`` keep firing at step S no
  matter how far ahead the producer runs;
* shutdown drains cleanly on completion, preemption and crash (no thread
  leaks), and resume after ``fast_forward`` stays aligned;
* goodput books "data" time only when the consumer actually stalls;
* the persistent compile cache gives a second process a
  ``compile/cache_hit`` and a smaller "compile" bucket.
"""

import csv
import json
import os
import threading
import time

import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu import telemetry as tel
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import ClusterConfig, TrainConfig
from dtf_tpu.data import load_mnist
from dtf_tpu.data.datasets import Dataset, DataSplits
from dtf_tpu.data.prefetch import DevicePrefetcher
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.train.trainer import Trainer


def _costs(logdir):
    """Full-precision cost rows from metrics.csv, in write order."""
    out = []
    with open(os.path.join(logdir, "metrics.csv")) as f:
        for rec in csv.reader(f):
            if rec and rec[0] != "step" and rec[1] == "cost":
                out.append((int(rec[0]), rec[2]))
    return out


def _fit(mesh8, logdir, *, prefetch, aot_warmup=True, chaos=None,
         max_steps=8, splits=None, optimizer=None, **cfg_kw):
    """One fresh-telemetry Trainer.fit on the 8-device mesh; returns
    (result, trainer)."""
    tel.reset()
    cfg = TrainConfig(batch_size=64, learning_rate=0.05, epochs=1,
                      log_frequency=1, seed=1, logdir=str(logdir),
                      prefetch=prefetch, aot_warmup=aot_warmup,
                      chaos=chaos, **cfg_kw)
    trainer = Trainer(Cluster(config=ClusterConfig(), mesh=mesh8),
                      MnistMLP(init_scale="fan_in"),
                      optimizer or optim.adam(1e-3), cfg)
    result = trainer.fit(splits if splits is not None else load_mnist(seed=1),
                         epochs=1, max_steps=max_steps)
    trainer.logger.close()
    return result, trainer


def _no_prefetch_threads():
    return not [t for t in threading.enumerate()
                if t.name == "dtf-device-prefetch" and t.is_alive()]


class TestDevicePrefetcher:
    """Unit tests against a plain produce(step) callable — no mesh."""

    def test_order_and_values_match_serial(self):
        pf = DevicePrefetcher(lambda s: np.full((2,), s), start_step=0,
                              num_batches=20, depth=3)
        got = [pf.get(s)[0] for s in range(20)]
        assert got == list(range(20))
        assert pf.close() == 0                 # completed: no overrun

    def test_error_surfaces_at_consuming_step(self):
        def produce(step):
            if step == 5:
                raise ValueError("boom at 5")
            return step
        pf = DevicePrefetcher(produce, start_step=0, num_batches=10, depth=4)
        assert [pf.get(s) for s in range(5)] == [0, 1, 2, 3, 4]
        with pytest.raises(ValueError, match="boom at 5"):
            pf.get(5)
        assert pf.delivered == 5
        pf.close()

    def test_out_of_order_consumption_rejected(self):
        pf = DevicePrefetcher(lambda s: s, start_step=3, num_batches=5)
        with pytest.raises(RuntimeError, match="out of order"):
            pf.get(4)
        assert pf.get(3) == 3
        pf.close()

    def test_production_is_depth_bounded(self):
        produced = []
        pf = DevicePrefetcher(lambda s: produced.append(s) or s,
                              start_step=0, num_batches=100, depth=2)
        deadline = time.time() + 5.0
        while len(produced) < 3 and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)                        # would run away if unbounded
        # depth items queued + one completed-but-blocked put in flight
        assert len(produced) <= 3
        assert pf.close() >= 2                 # those batches ARE consumed
        assert _no_prefetch_threads()

    def test_close_mid_stream_joins_and_reports_overrun(self):
        pf = DevicePrefetcher(lambda s: s, start_step=0, num_batches=50,
                              depth=2)
        assert pf.get(0) == 0
        overrun = pf.close()
        assert 0 <= overrun <= 3
        assert _no_prefetch_threads()
        assert pf.close() == overrun           # idempotent

    def test_stall_books_data_time_slow_producer(self):
        tel.reset()
        tracker = tel.get_tracker()
        pf = DevicePrefetcher(lambda s: time.sleep(0.05) or s,
                              start_step=0, num_batches=4, depth=2)
        for s in range(4):
            assert pf.get(s) == s
        pf.close()
        # the consumer outpaced the producer: real stalls were booked
        assert tracker.buckets["data"] > 0.03
        assert tel.gauge("data/prefetch_stall_s").value > 0.03

    def test_no_stall_books_nothing_fast_producer(self):
        tel.reset()
        tracker = tel.get_tracker()
        pf = DevicePrefetcher(lambda s: s, start_step=0, num_batches=4,
                              depth=4)
        time.sleep(0.3)                        # queue fills while we "compute"
        for s in range(4):
            pf.get(s)
        pf.close()
        # fully overlapped: the instrument exists but reads (near) zero
        assert tracker.buckets["data"] < 0.05
        assert tel.gauge("data/prefetch_stall_s").value is not None

    def test_process_shard_streams_reassemble_under_prefetch(self):
        """Simulated multi-process: each host's prefetched ProcessShard
        stream must reassemble into exactly the serial global batches —
        the multi-host feed contract survives the producer thread."""
        def mk():
            n = 64
            imgs = np.arange(n, dtype=np.float32)[:, None]
            labels = np.eye(2, dtype=np.float32)[np.arange(n) % 2]
            return Dataset(imgs, labels, seed=3)

        serial = mk()
        views = [mk().process_shard(k, 2) for k in range(2)]
        pfs = [DevicePrefetcher(lambda s, v=v: v.next_batch(8),
                                start_step=0, num_batches=10, depth=2)
               for v in views]
        for step in range(10):                 # crosses an epoch reshuffle
            gx, gy = serial.next_batch(16)
            parts = [pf.get(step) for pf in pfs]
            np.testing.assert_array_equal(
                np.concatenate([p[0] for p in parts]), gx)
            np.testing.assert_array_equal(
                np.concatenate([p[1] for p in parts]), gy)
        for pf in pfs:
            assert pf.close() == 0


class TestTrainerTrajectory:
    def test_loss_sequence_bitwise_identical(self, mesh8, tmp_path):
        """THE acceptance proof (single-process): serial path (prefetch 0,
        no AOT — the exact pre-change loop) vs the full new path
        (prefetch 2 + AOT-compiled step) produce bitwise-identical cost
        rows; overlap shows up as a strictly smaller "data" bucket."""
        _fit(mesh8, tmp_path / "p0", prefetch=0, aot_warmup=False)
        d0 = json.load(open(tmp_path / "p0" / "telemetry.json"))
        _fit(mesh8, tmp_path / "p2", prefetch=2, aot_warmup=True)
        d2 = json.load(open(tmp_path / "p2" / "telemetry.json"))
        c0, c2 = _costs(tmp_path / "p0"), _costs(tmp_path / "p2")
        assert len(c0) == 8
        assert c0 == c2
        # overlap is measurable: data time off the hot path
        assert d2["goodput"]["data_s"] < d0["goodput"]["data_s"]
        # the new instruments landed
        assert "data/prefetch_depth" in d2["metrics"]
        assert "data/prefetch_stall_s" in d2["metrics"]
        assert d2["metrics"]["compile/aot_s"]["value"] > 0
        assert _no_prefetch_threads()

    def test_chaos_fires_at_the_consumed_step(self, mesh8, tmp_path):
        """nan_grad@3 + loader_error@2 with the producer running ahead:
        the NaN lands exactly in the step-4 cost row (the update computed
        from batch 3), the loader error is retried on the producer
        thread, and the whole chaos'd trajectory stays bitwise-identical
        to the serial chaos'd run."""
        chaos = "nan_grad@3,loader_error@2"
        r0, _ = _fit(mesh8, tmp_path / "p0", prefetch=0, aot_warmup=False,
                     chaos=chaos, max_steps=6)
        c0 = _costs(tmp_path / "p0")
        r2, _ = _fit(mesh8, tmp_path / "p2", prefetch=2, chaos=chaos,
                     max_steps=6)
        c2 = _costs(tmp_path / "p2")
        d2 = json.load(open(tmp_path / "p2" / "telemetry.json"))
        assert c0 == c2
        assert r0["skipped_steps"] == r2["skipped_steps"] == 1
        nan_steps = [s for s, v in c2 if v == "nan"]
        assert nan_steps == [4]
        assert d2["metrics"]["data/fetch_retries_total"]["value"] == 1
        assert d2["metrics"]["chaos/faults_fired_total"]["value"] == 2

    def test_resume_after_fast_forward_stays_aligned(self, mesh8, tmp_path):
        """checkpoint at 3 -> fresh trainer + fresh dataset resumes with
        prefetch 2 -> the continued trajectory equals one uninterrupted
        serial run, bitwise."""
        _fit(mesh8, tmp_path / "ab", prefetch=2, max_steps=6,
             checkpoint_every=3)
        _fit(mesh8, tmp_path / "ab", prefetch=2, max_steps=12,
             checkpoint_every=3, resume=True)
        _fit(mesh8, tmp_path / "ref", prefetch=0, aot_warmup=False,
             max_steps=12)
        resumed = _costs(tmp_path / "ab")
        ref = _costs(tmp_path / "ref")
        # the resumed file holds both attempts; compare by step number
        by_step = {s: v for s, v in resumed}          # latest attempt wins
        assert {s: v for s, v in ref} == by_step

    def test_producer_error_propagates_at_failing_step(self, mesh8,
                                                       tmp_path):
        """A persistently-failing fetch (retry budget exhausted on the
        producer thread) must raise on the MAIN thread when the loop
        reaches the failing step — after cleanly consuming every earlier
        batch."""
        from dtf_tpu.utils.retry import RetryExhausted

        class FailsFrom:
            """next_batch contract; batch index >= k always raises."""

            def __init__(self, base, k):
                self.base, self.k, self.batches_consumed = base, k, 0

            @property
            def num_examples(self):
                return self.base.num_examples

            def next_batch(self, bs):
                if self.batches_consumed >= self.k:
                    raise OSError("disk on fire")
                self.batches_consumed += 1
                return self.base.next_batch(bs)

        base = load_mnist(seed=1).train
        splits = DataSplits(train=FailsFrom(base, 3), test=None)
        tel.reset()
        cfg = TrainConfig(batch_size=64, learning_rate=0.05, epochs=1,
                          log_frequency=1, seed=1,
                          logdir=str(tmp_path), prefetch=2)
        trainer = Trainer(Cluster(config=ClusterConfig(), mesh=mesh8),
                          MnistMLP(init_scale="fan_in"), optim.adam(1e-3),
                          cfg)
        with pytest.raises(RetryExhausted):
            trainer.fit(splits, epochs=1, max_steps=8)
        trainer.logger.close()
        assert trainer._host_step == 3         # steps 0..2 consumed cleanly
        assert _no_prefetch_threads()

    def test_preemption_drains_producer_cleanly(self, mesh8, tmp_path):
        """chaos sigterm mid-epoch: the fit returns preempted=True and the
        producer thread is joined (no leak); if the producer over-ran the
        break point, re-fitting the SAME dataset object fails loud
        instead of silently serving shifted batches."""
        splits = load_mnist(seed=1)
        res, trainer = _fit(mesh8, tmp_path, prefetch=2, max_steps=50,
                            chaos="sigterm@3", checkpoint_every=100,
                            splits=splits)
        assert res["preempted"] is True
        assert _no_prefetch_threads()
        overrun = splits.train.batches_consumed - trainer._host_step
        assert overrun >= 0
        if overrun:                    # producer timing-dependent
            with pytest.raises(RuntimeError, match="ahead of the"):
                trainer.fit(splits, epochs=1, max_steps=50)
            trainer.logger.close()

    def test_aot_skipped_without_shape_probe(self, mesh8, tmp_path):
        """CallableDataset has no ``examples`` accessor: AOT warmup must
        fall back silently to compile-on-first-dispatch and still train
        (through the prefetcher)."""
        from dtf_tpu.data.datasets import CallableDataset

        rng = np.random.default_rng(0)
        x = rng.random((64, 784)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[np.arange(64) % 10]
        train = CallableDataset(lambda i: (x, y), 64, 10)
        res, trainer = _fit(mesh8, tmp_path, prefetch=2, max_steps=3,
                            splits=DataSplits(train=train, test=None))
        assert res["steps"] == 3
        assert trainer._compiled_step is None
        assert trainer._compile_seen is True


class TestCompileCache:
    _CHILD = """\
import sys
import jax
from dtf_tpu import optim
from dtf_tpu.cluster import Cluster
from dtf_tpu.config import ClusterConfig, TrainConfig
from dtf_tpu.data import load_mnist
from dtf_tpu.models.mlp import MnistMLP
from dtf_tpu.parallel.mesh import make_mesh
from dtf_tpu.train.trainer import Trainer

cache, logdir = sys.argv[1], sys.argv[2]
cfg = TrainConfig(batch_size=64, learning_rate=0.05, epochs=1,
                  log_frequency=2, seed=1, logdir=logdir,
                  compile_cache=cache)
mesh = make_mesh("data=-1")
t = Trainer(Cluster(config=ClusterConfig(), mesh=mesh),
            MnistMLP(init_scale="fan_in"), optim.adam(1e-3), cfg)
t.fit(load_mnist(seed=1), epochs=1, max_steps=3)
t.logger.close()
"""

    def test_second_process_hits_cache_and_compiles_less(self, tmp_path):
        """THE acceptance proof for compile reuse: two processes pointed
        at the same --compile_cache dir; the second records
        compile/cache_hit >= 1 and a smaller "compile" goodput bucket."""
        import subprocess
        import sys
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cache = str(tmp_path / "xla_cache")
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PYTHONPATH": root + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        docs = []
        for run in ("cold", "warm"):
            logdir = str(tmp_path / run)
            p = subprocess.run(
                [sys.executable, "-c", self._CHILD, cache, logdir],
                capture_output=True, text=True, timeout=240, env=env,
                cwd=root)
            assert p.returncode == 0, p.stdout + p.stderr
            docs.append(json.load(open(os.path.join(logdir,
                                                    "telemetry.json"))))
        cold, warm = docs
        assert cold["metrics"].get("compile/cache_miss",
                                   {}).get("value", 0) >= 1
        assert warm["metrics"].get("compile/cache_hit",
                                   {}).get("value", 0) >= 1
        assert (warm["goodput"]["compile_s"]
                < cold["goodput"]["compile_s"])

    def test_enable_is_idempotent_and_feature_gated(self, tmp_path):
        import jax

        from dtf_tpu.train import compile_cache
        old_dir = jax.config.jax_compilation_cache_dir
        old_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            d = str(tmp_path / "cc")
            assert compile_cache.enable(d) == os.path.abspath(d)
            assert compile_cache.enable(d) == os.path.abspath(d)
            assert jax.config.jax_compilation_cache_dir == os.path.abspath(d)
        finally:
            jax.config.update("jax_compilation_cache_dir", old_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              old_min)


@pytest.mark.slow
class TestMultiProcessPrefetch:
    def test_two_process_trajectory_identical(self, tmp_path):
        """True 2-process run (per-host sharded feed): the coordinator's
        cost rows are bitwise-identical between prefetch 0 and 2."""
        import sys

        from tests.test_multiprocess import REPO_ROOT, free_port, run_workers
        script = os.path.join(REPO_ROOT, "tests", "_mp_prefetch.py")
        rows = {}
        for depth in (0, 2):
            port = free_port()
            logdir = str(tmp_path / f"pf{depth}")
            outs = run_workers(
                [[sys.executable, script, str(task), f"localhost:{port}",
                  str(depth), logdir] for task in range(2)],
                n_local_devices=4, timeout=300)
            assert all("MP_PREFETCH_DONE" in o for o in outs)
            # SPMD: both tasks report the identical final cost
            finals = {o.split("final_cost=")[1].splitlines()[0]
                      for o in outs}
            assert len(finals) == 1
            rows[depth] = _costs(logdir)
        assert rows[0] and rows[0] == rows[2]
