"""T5-style encoder-decoder: decoder causality, cross-attention
connectivity, pad masking, KV-cache generation consistency with the
teacher-forced forward, loss masking, and an end-to-end copy-task
convergence run on the simulated mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.models.t5 import T5, T5Config


@pytest.fixture(scope="module")
def model():
    return T5(T5Config.tiny())


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.key(0))


def rand_tokens(key, shape, vocab=64, lo=2):
    return jnp.asarray(
        np.random.default_rng(key).integers(lo, vocab, shape), jnp.int32)


class TestForward:
    def test_logits_shape(self, model, params):
        src, tgt_in = rand_tokens(0, (2, 12)), rand_tokens(1, (2, 9))
        logits = model.apply(params, (src, tgt_in))
        assert logits.shape == (2, 9, 64)
        assert logits.dtype == jnp.float32

    def test_decoder_causality(self, model, params):
        """Changing a future decoder token must not change past logits."""
        src = rand_tokens(2, (1, 10))
        a = np.asarray(rand_tokens(3, (1, 12)))
        b = a.copy()
        b[0, 8:] = np.asarray(rand_tokens(4, (4,)))
        la = model.apply(params, (src, jnp.asarray(a)))
        lb = model.apply(params, (src, jnp.asarray(b)))
        np.testing.assert_allclose(la[0, :8], lb[0, :8], atol=1e-5)
        assert not np.allclose(la[0, 8:], lb[0, 8:])

    def test_cross_attention_connects_encoder(self, model, params):
        """Changing the SOURCE changes every decoder position's logits —
        the cross-attention path is live."""
        tgt_in = rand_tokens(5, (1, 8))
        la = model.apply(params, (rand_tokens(6, (1, 10)), tgt_in))
        lb = model.apply(params, (rand_tokens(7, (1, 10)), tgt_in))
        assert not np.allclose(la, lb)

    def test_padded_source_equals_short_source(self, model, params):
        """A source with a padded tail must produce the same decoder
        logits as the unpadded short source: encoder self-attention and
        decoder cross-attention both mask pad positions, so the pads are
        invisible end to end."""
        short = np.asarray(rand_tokens(8, (1, 6)))
        padded = np.concatenate(
            [short, np.zeros((1, 4), np.int32)], axis=1)   # pad_id tail
        tgt_in = rand_tokens(9, (1, 6))
        la = model.apply(params, (jnp.asarray(short), tgt_in))
        lb = model.apply(params, (jnp.asarray(padded), tgt_in))
        np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                   atol=1e-5)

    def test_loss_ignores_pad_targets(self, model, params):
        src = rand_tokens(10, (2, 10))
        tgt = np.asarray(rand_tokens(11, (2, 8)))
        tgt_padded = tgt.copy()
        tgt_padded[:, 6:] = 0
        l_full, _ = model.loss(params, {"src": src,
                                        "tgt": jnp.asarray(tgt_padded)})
        # manually: loss over only the first 6 positions
        logits = model.apply(
            params, (src, model._shift_right(jnp.asarray(tgt_padded))))
        logp = jax.nn.log_softmax(logits, axis=-1)
        tl = np.asarray(jnp.take_along_axis(
            logp, jnp.asarray(tgt_padded)[..., None], axis=-1))[..., 0]
        manual = -tl[:, :6].mean()
        assert float(l_full) == pytest.approx(float(manual), rel=1e-5)


class TestChunkedLoss:
    @pytest.mark.parametrize("chunk", [8, 6])   # even split / pad path
    def test_chunked_matches_dense(self, model, params, chunk):
        """cfg.loss_chunk: loss, grads and accuracy must match the dense
        head exactly (chunk 6 exercises the pad-to-multiple path; pad
        rows carry pad_id targets, so the mask drops them)."""
        src = np.array(rand_tokens(11, (4, 16)))
        src[:, 12:] = 0                          # real padding
        batch = {"src": jnp.asarray(src),
                 "tgt": jnp.asarray(src[:, ::-1].copy())}
        mc = T5(T5Config.tiny(loss_chunk=chunk, label_smoothing=0.1))
        md = T5(T5Config.tiny(label_smoothing=0.1))
        ld, gd = jax.value_and_grad(lambda p: md.loss(p, batch)[0])(params)
        lc, gc = jax.value_and_grad(lambda p: mc.loss(p, batch)[0])(params)
        assert abs(float(ld) - float(lc)) < 1e-6
        for a, b in zip(jax.tree.leaves(gd), jax.tree.leaves(gc),
                        strict=True):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6)
        assert abs(float(md.loss(params, batch)[1]["accuracy"])
                   - float(mc.loss(params, batch)[1]["accuracy"])) < 1e-6


class TestGeneration:
    def test_greedy_matches_teacher_forced(self, model, params):
        """KV-cache decode (+ pre-projected cross K/V) must reproduce the
        teacher-forced forward's argmax chain."""
        src = rand_tokens(12, (2, 10))
        gen = model.generate(params, src, 6, temperature=0.0)
        assert gen.shape == (2, 6)
        # replay with the parallel decoder
        cur = jnp.full((2, 1), 1, jnp.int32)        # BOS
        for t in range(6):
            logits = model.apply(params, (src, cur))
            nxt = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
            np.testing.assert_array_equal(np.asarray(nxt),
                                          np.asarray(gen[:, t]),
                                          err_msg=f"t={t}")
            cur = jnp.concatenate([cur, nxt[:, None]], axis=1)

    def test_sampling_deterministic_per_key(self, model, params):
        src = rand_tokens(13, (1, 8))
        a = model.generate(params, src, 5, temperature=1.0,
                           rng=jax.random.key(3))
        b = model.generate(params, src, 5, temperature=1.0,
                           rng=jax.random.key(3))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestPipelined:
    @pytest.mark.parametrize("positions", ["relative", "absolute"])
    def test_matches_sequential_stacks(self, model, params, positions):
        """Pipelined encoder+decoder (GPipe over both stacks, relpos table
        tiled into stage params) must equal the lax.scan path — loss and
        gradients."""
        from dtf_tpu.parallel.mesh import make_mesh

        mesh = make_mesh("data=4,pipe=2")
        kw = {} if positions == "relative" else {"positions": "absolute",
                                                 "norm": "layernorm"}
        seq_model = T5(T5Config.tiny(**kw))
        pp_model = T5(T5Config.tiny(pipeline_mesh=mesh,
                                    pipeline_microbatches=2, **kw))
        p = seq_model.init(jax.random.key(3))
        src = rand_tokens(10, (16, 8))
        src = src.at[:, -2:].set(0)              # padded tail
        tgt = rand_tokens(11, (16, 8))
        batch = {"src": src, "tgt": tgt}

        (l_p, _), g_p = jax.value_and_grad(
            lambda q: pp_model.loss(q, batch), has_aux=True)(p)
        (l_s, _), g_s = jax.value_and_grad(
            lambda q: seq_model.loss(q, batch), has_aux=True)(p)
        np.testing.assert_allclose(l_p, l_s, rtol=1e-6)
        flat_p = jax.tree_util.tree_leaves_with_path(g_p)
        flat_s = dict(jax.tree_util.tree_leaves_with_path(g_s))
        for path, leaf in flat_p:
            np.testing.assert_allclose(
                leaf, flat_s[path], atol=3e-5,
                err_msg=jax.tree_util.keystr(path))


class TestFlopsAccounting:
    def test_encoder_decoder_split_not_double_counted(self):
        """Each stack's params x its own side's tokens: for equal src/tgt
        lengths and a symmetric model this is ~half of the naive
        6·P_total·(S+T) (which charges every param for both sides)."""
        from dtf_tpu.nn.core import count_params
        m = T5(T5Config.tiny())
        p = m.init(jax.random.key(0))
        f = m.train_flops_per_example(p)
        naive = 6.0 * count_params(p) * (m.cfg.max_src_len
                                         + m.cfg.max_tgt_len)
        assert 0.35 < f / naive < 0.65
        # head dominates tiny configs; still strictly positive and finite
        assert np.isfinite(f) and f > 0


class Test1F1B:
    @pytest.mark.parametrize("positions", ["relative", "absolute"])
    def test_grads_match_dense_path(self, positions):
        """Decoder-stack 1F1B (encoder output through the schedule's
        differentiable ctx, encoder GPipe-by-AD): loss and every gradient
        must match jax.grad of the unpipelined loss.  Full-length targets
        (see the loss-semantics note in T5.pipeline_loss_and_grads); the
        padded SOURCE is fine — ctx_valid masks it identically."""
        from dtf_tpu.parallel.mesh import make_mesh

        mesh = make_mesh("data=4,pipe=2")
        kw = {} if positions == "relative" else {"positions": "absolute",
                                                 "norm": "layernorm"}
        seq_model = T5(T5Config.tiny(**kw))
        pp_model = T5(T5Config.tiny(pipeline_mesh=mesh,
                                    pipeline_microbatches=4,
                                    pipeline_schedule="1f1b", **kw))
        p = seq_model.init(jax.random.key(3))
        src = rand_tokens(10, (16, 8))
        src = src.at[:, -2:].set(0)              # padded tail
        tgt = jnp.maximum(rand_tokens(11, (16, 8)), 2)   # no pad targets
        batch = {"src": src, "tgt": tgt}

        l_p, metrics, g_p = pp_model.pipeline_loss_and_grads(p, batch)
        assert "accuracy" not in metrics          # schedule reduces loss only
        (l_s, _), g_s = jax.value_and_grad(
            lambda q: seq_model.loss(q, batch), has_aux=True)(p)
        np.testing.assert_allclose(float(l_p), float(l_s), rtol=1e-5)
        flat_p = jax.tree_util.tree_leaves_with_path(g_p)
        flat_s = dict(jax.tree_util.tree_leaves_with_path(g_s))
        for path, leaf in flat_p:
            np.testing.assert_allclose(
                leaf, flat_s[path], atol=3e-4,
                err_msg=jax.tree_util.keystr(path))


class TestTraining:
    def test_learns_copy_task(self, mesh8):
        """End-to-end: tiny T5 learns to copy the source sequence (the
        canonical seq2seq smoke test) well above chance.

        Uses the absolute-position/LayerNorm config: copy alignment is a
        direct position lookup there, so the tiny model converges in a
        CPU-friendly step budget.  The default (relative positions) must
        learn content-based alignment instead — measurably slower on this
        deliberately position-keyed task; its learning signal is asserted
        separately below."""
        from dtf_tpu import optim
        from dtf_tpu.parallel.mesh import make_mesh
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        mesh = make_mesh("data=8")
        model = T5(T5Config.tiny(positions="absolute", norm="layernorm"))
        opt = optim.adam(3e-3)
        state = init_state(model, opt, seed=0, mesh=mesh)
        step = make_train_step(model.loss, opt, mesh, donate=False)
        rng = np.random.default_rng(0)
        accs = []
        for i in range(350):     # ~0.48 acc by 300, ~0.99 by 400
            toks = rng.integers(2, 64, (16, 12)).astype(np.int32)
            batch = put_global_batch(mesh, {"src": toks, "tgt": toks})
            state, m = step(state, batch, jax.random.key(i))
            accs.append(float(m["accuracy"]))
        assert accs[-1] > 0.6, accs[-5:]    # chance ~ 1/62

    def test_relpos_default_learns(self, mesh8):
        """The default (relative-position + RMSNorm) T5 reduces loss and
        lifts accuracy well above chance on the copy task — slower than
        absolute positions here by design (see above), but clearly
        learning (measured: ~0.19 acc by step 300 vs chance ~0.016)."""
        from dtf_tpu import optim
        from dtf_tpu.parallel.mesh import make_mesh
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        mesh = make_mesh("data=8")
        model = T5(T5Config.tiny())
        assert model.relative                    # relpos IS the default
        opt = optim.adam(3e-3)
        state = init_state(model, opt, seed=0, mesh=mesh)
        step = make_train_step(model.loss, opt, mesh, donate=False)
        rng = np.random.default_rng(0)
        losses, accs = [], []
        for i in range(200):
            toks = rng.integers(2, 64, (16, 12)).astype(np.int32)
            batch = put_global_batch(mesh, {"src": toks, "tgt": toks})
            state, m = step(state, batch, jax.random.key(i))
            losses.append(float(m["loss"]))
            accs.append(float(m["accuracy"]))
        # measured: 4.17 -> 3.46 by step 200, acc ~0.1 (chance 1/62)
        assert losses[-1] < 0.88 * losses[0], (losses[0], losses[-1])
        assert np.mean(accs[-10:]) > 0.05, accs[-10:]
