"""Profiler hook + determinism-check utilities (SURVEY.md §5.1, §5.2)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.utils.profiling import (assert_replicas_agree, fingerprint,
                                     trace)


class TestFingerprint:
    def test_bitwise_sensitivity(self):
        a = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        b = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        assert fingerprint(a) == fingerprint(b)
        # a single-ULP change flips the digest
        c = {"w": jnp.ones((4, 4)).at[0, 0].set(
                 np.nextafter(np.float32(1.0), np.float32(2.0))),
             "b": jnp.zeros((4,))}
        assert fingerprint(a) != fingerprint(c)

    def test_order_stability_across_dtypes(self):
        t = {"x": jnp.arange(6, dtype=jnp.int32),
             "y": jnp.arange(6, dtype=jnp.float32)}
        assert fingerprint(t) == fingerprint(t)
        assert fingerprint(t) != fingerprint({"x": t["y"], "y": t["x"]})

    def test_single_process_agree_noop(self):
        assert_replicas_agree({"loss": jnp.float32(1.5)})   # must not raise


class TestTraceHook:
    def test_trace_writes_profile(self, tmp_path):
        logdir = str(tmp_path / "prof")
        with trace(logdir):
            jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
        found = []
        for root, _, files in os.walk(logdir):
            found += [f for f in files if f.endswith((".trace.json.gz",
                                                      ".xplane.pb"))]
        assert found, f"no trace artifacts under {logdir}"

    def test_trainer_profile_window(self, mesh8, tmp_path):
        from dtf_tpu import optim
        from dtf_tpu.cluster import Cluster
        from dtf_tpu.config import ClusterConfig, TrainConfig
        from dtf_tpu.data import load_mnist
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import Trainer

        prof = str(tmp_path / "prof")
        cfg = TrainConfig(batch_size=512, epochs=1, log_frequency=1000,
                          seed=1, logdir=str(tmp_path),
                          profile_dir=prof, profile_start=2, profile_steps=2,
                          determinism_every=5)
        cluster = Cluster(config=ClusterConfig(), mesh=mesh8)
        t = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                    cfg)
        t.fit(load_mnist(seed=1), epochs=1)
        found = []
        for root, _, files in os.walk(prof):
            found += [f for f in files if f.endswith((".trace.json.gz",
                                                      ".xplane.pb"))]
        assert found, "trainer profile window produced no trace"
