"""Profiler hook + determinism-check utilities (SURVEY.md §5.1, §5.2)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.utils.profiling import (assert_replicas_agree, fingerprint,
                                     trace)


class TestFingerprint:
    def test_bitwise_sensitivity(self):
        a = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        b = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
        assert fingerprint(a) == fingerprint(b)
        # a single-ULP change flips the digest
        c = {"w": jnp.ones((4, 4)).at[0, 0].set(
                 np.nextafter(np.float32(1.0), np.float32(2.0))),
             "b": jnp.zeros((4,))}
        assert fingerprint(a) != fingerprint(c)

    def test_order_stability_across_dtypes(self):
        t = {"x": jnp.arange(6, dtype=jnp.int32),
             "y": jnp.arange(6, dtype=jnp.float32)}
        assert fingerprint(t) == fingerprint(t)
        assert fingerprint(t) != fingerprint({"x": t["y"], "y": t["x"]})

    def test_single_process_agree_noop(self):
        assert_replicas_agree({"loss": jnp.float32(1.5)})   # must not raise


class TestTraceHook:
    def test_trace_writes_profile(self, tmp_path):
        logdir = str(tmp_path / "prof")
        with trace(logdir):
            jnp.dot(jnp.ones((64, 64)), jnp.ones((64, 64))).block_until_ready()
        found = []
        for root, _, files in os.walk(logdir):
            found += [f for f in files if f.endswith((".trace.json.gz",
                                                      ".xplane.pb"))]
        assert found, f"no trace artifacts under {logdir}"

    def test_trainer_profile_window(self, mesh8, tmp_path):
        from dtf_tpu import optim
        from dtf_tpu.cluster import Cluster
        from dtf_tpu.config import ClusterConfig, TrainConfig
        from dtf_tpu.data import load_mnist
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import Trainer

        prof = str(tmp_path / "prof")
        cfg = TrainConfig(batch_size=512, epochs=1, log_frequency=1000,
                          seed=1, logdir=str(tmp_path),
                          profile_dir=prof, profile_start=2, profile_steps=2,
                          determinism_every=5)
        cluster = Cluster(config=ClusterConfig(), mesh=mesh8)
        t = Trainer(cluster, MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                    cfg)
        t.fit(load_mnist(seed=1), epochs=1)
        found = []
        for root, _, files in os.walk(prof):
            found += [f for f in files if f.endswith((".trace.json.gz",
                                                      ".xplane.pb"))]
        assert found, "trainer profile window produced no trace"


class TestSummarizeTrace:
    def test_aggregates_device_ops(self, tmp_path):
        """summarize_trace sums device-pid op durations and ignores host
        events — validated on a synthetic Chrome-trace file in the layout
        jax.profiler writes."""
        import gzip
        import json

        from dtf_tpu.utils.profiling import summarize_trace

        run = tmp_path / "plugins" / "profile" / "2026_01_01"
        run.mkdir(parents=True)
        events = [
            {"ph": "M", "pid": 3, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 9, "name": "process_name",
             "args": {"name": "/host:CPU"}},
            {"ph": "M", "pid": 7, "name": "process_name"},  # no args: skip
            # device pid stacks covering lanes; only "XLA Ops" counts
            {"ph": "M", "pid": 3, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "M", "pid": 3, "tid": 2, "name": "thread_name",
             "args": {"name": "XLA Modules"}},
            {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.1",
             "dur": 2_000_000},
            {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.1",
             "dur": 1_000_000},
            {"ph": "X", "pid": 3, "tid": 1, "name": "copy.2",
             "dur": 500_000},
            {"ph": "X", "pid": 3, "tid": 2, "name": "jit_step",
             "dur": 3_500_000},              # module span covers the ops
            {"ph": "X", "pid": 9, "name": "host_thing", "dur": 9_000_000},
        ]
        with gzip.open(run / "vm.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)

        rows = summarize_trace(str(tmp_path))
        assert rows[0] == ("fusion.1", 3.0)
        assert rows[1] == ("copy.2", 0.5)
        names = [n for n, _ in rows]
        assert "host_thing" not in names       # host pid excluded
        assert "jit_step" not in names         # covering lane excluded

    def test_missing_trace_raises(self, tmp_path):
        import pytest as _pytest

        from dtf_tpu.utils.profiling import summarize_trace
        with _pytest.raises(FileNotFoundError, match="trace.json.gz"):
            summarize_trace(str(tmp_path))


class TestAttnSweep:
    @pytest.mark.slow
    def test_sweep_rows_dedupe_and_report(self):
        """attn_sweep (the r4 MFU close-or-retire evidence tool): at T=128
        the whole block grid clamps to one combo, plus the Dh ablation —
        4 rows (fwd + fwd+bwd each), every row with positive time and
        FLOPs, and the Dh=128 row carries the SAME FLOPs as the Dh=64 row
        (the ablation's whole point)."""
        from dtf_tpu.bench.breakdown import attn_sweep

        rows = attn_sweep("bert", batch=1, seq=128)
        names = [r.name for r in rows]
        assert len(names) == len(set(names))
        assert len(rows) == 4
        assert all(r.seconds > 0 and r.flops > 0 for r in rows)
        by = {r.name: r for r in rows}
        f64 = by["fwd H12 Dh64 bq128 bk128"].flops
        # the ablation tag names the RESOLVED tiling (clamped at T=128)
        f128 = by["fwd H6 Dh128 (same FLOPs) bq128 bk128"].flops
        assert f64 == f128


class TestProfileSummaryFlag:
    @pytest.mark.slow
    def test_summary_prints_after_fit(self, tmp_path, capsys):
        """--profile_summary: after a profiled run the trainer prints
        [trace] lines (real per-op rows on TPU; an explicit no-device-
        rows note on host-only backends — never silence)."""
        from dtf_tpu.workloads import lm

        rc = lm.main(["--preset", "tiny", "--steps", "6", "--batch_size",
                      "8", "--profile_dir", str(tmp_path / "prof"),
                      "--profile_start", "3", "--profile_steps", "2",
                      "--profile_summary", "--logdir",
                      str(tmp_path / "log")])
        assert rc == 0
        out = capsys.readouterr().out
        # host backend: the explicit no-device-rows note, and never the
        # failure branch
        assert ("no device-op rows" in out) or ("ms/step" in out)
        assert "summary unavailable" not in out

    @pytest.mark.slow
    def test_summary_without_dir_rejected(self, tmp_path):
        from dtf_tpu.workloads import lm

        with pytest.raises(ValueError, match="profile_dir"):
            lm.main(["--preset", "tiny", "--steps", "2", "--batch_size",
                     "8", "--profile_summary",
                     "--logdir", str(tmp_path / "log")])
