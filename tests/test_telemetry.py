"""Telemetry spine (dtf_tpu/telemetry): span nesting/export round-trip,
registry snapshot determinism, goodput arithmetic (incl. under injected
--chaos faults), metrics.csv attempt de-duplication, naming-scheme lint,
and a golden-output test for the report CLI on a fixture logdir."""

import glob
import json
import os

import numpy as np
import pytest

import dtf_tpu.telemetry as tel
from dtf_tpu.telemetry.goodput import CATEGORIES, GoodputTracker
from dtf_tpu.telemetry.registry import MetricRegistry
from dtf_tpu.telemetry.spans import Tracer, export_chrome_trace, read_spans


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    """Process-wide registry/tracker/tracer state must not leak between
    tests (or in from earlier test files in the same pytest process)."""
    tel.reset()
    yield
    tel.reset()


class TestSpans:
    def test_nesting_and_roundtrip(self, tmp_path):
        path = str(tmp_path / "spans.p0.jsonl")
        tr = Tracer(path, process=0)
        with tr.span("train/step", step=7):
            with tr.span("checkpoint/save", step=7):
                pass
        tr.instant("chaos/nan_grad", step=17)
        tr.close()
        recs = read_spans(path)
        by_name = {r["name"]: r for r in recs}
        # inner span closes (and is written) first; both recorded
        assert recs[0]["name"] == "checkpoint/save"
        outer, inner = by_name["train/step"], by_name["checkpoint/save"]
        assert outer["ph"] == inner["ph"] == "X"
        # structural nesting: depth + parent, child window inside parent
        assert outer["args"]["depth"] == 0 and "parent" not in outer["args"]
        assert inner["args"]["depth"] == 1
        assert inner["args"]["parent"] == "train/step"
        assert inner["ts"] >= outer["ts"]
        assert (inner["ts"] + inner["dur"]
                <= outer["ts"] + outer["dur"] + 1e3)   # 1ms clock slack
        assert outer["args"]["step"] == 7
        inst = by_name["chaos/nan_grad"]
        assert inst["ph"] == "i" and inst["args"]["step"] == 17

    def test_export_chrome_trace(self, tmp_path):
        tr = Tracer(str(tmp_path / "spans.p0.jsonl"), process=0)
        with tr.span("train/fit"):
            pass
        tr.close()
        tr1 = Tracer(str(tmp_path / "spans.p1.jsonl"), process=1)
        with tr1.span("train/fit"):
            pass
        tr1.close()
        out = str(tmp_path / "trace.json")
        n = export_chrome_trace(str(tmp_path), out)
        doc = json.load(open(out))
        events = doc["traceEvents"]
        # 2 spans + per-host process_name AND process_sort_index metas
        # (one named, sort-ordered Perfetto track-group per host — the
        # fleet plane's merged-trace contract)
        assert n == len(events) == 6
        assert {e["pid"] for e in events} == {0, 1}
        for meta in ("process_name", "process_sort_index"):
            assert sum(1 for e in events
                       if e.get("ph") == "M" and e["name"] == meta) == 2

    def test_disabled_tracer_is_noop(self, tmp_path):
        tr = Tracer(None)
        with tr.span("train/step"):
            pass
        tr.instant("chaos/stall")
        assert not tr.enabled
        assert not list((tmp_path).iterdir())

    def test_bad_name_rejected(self, tmp_path):
        tr = Tracer(str(tmp_path / "s.jsonl"))
        with pytest.raises(ValueError, match="naming scheme"):
            with tr.span("Not A Name"):
                pass

    def test_torn_tail_dropped(self, tmp_path):
        path = str(tmp_path / "spans.p0.jsonl")
        tr = Tracer(path)
        with tr.span("train/step"):
            pass
        tr.close()
        with open(path, "a") as f:
            f.write('{"name": "train/')       # SIGKILL mid-write
        assert [r["name"] for r in read_spans(path)] == ["train/step"]


class TestRegistry:
    def test_snapshot_deterministic(self):
        def feed(reg):
            # creation order must not matter
            reg.gauge("throughput/tokens_per_s").set(10.0)
            reg.counter("event/rollback").inc(2)
            reg.histogram("throughput/step_ms").observe(4.0)
            reg.histogram("throughput/step_ms").observe(8.0)
        a, b = MetricRegistry(), MetricRegistry()
        feed(a)
        b.histogram("throughput/step_ms")     # registered earlier, same end
        feed(b)
        assert a.snapshot() == b.snapshot()
        snap = a.snapshot()
        assert list(snap) == sorted(snap)
        assert snap["event/rollback"] == {"type": "counter", "value": 2}
        h = snap["throughput/step_ms"]
        assert (h["count"], h["sum"], h["min"], h["max"], h["mean"]) == \
            (2, 12.0, 4.0, 8.0, 6.0)

    def test_type_conflict_rejected(self):
        reg = MetricRegistry()
        reg.counter("event/rollback")
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("event/rollback")

    def test_write_json_atomic(self, tmp_path):
        reg = MetricRegistry()
        reg.gauge("mfu/pct_peak").set(41.5)
        path = str(tmp_path / "telemetry.json")
        reg.write_json(path, extra={"run": "x"})
        doc = json.load(open(path))
        assert doc["metrics"]["mfu/pct_peak"]["value"] == 41.5
        assert doc["run"] == "x"
        assert not os.path.exists(path + ".tmp")


class TestGoodput:
    def test_arithmetic(self):
        t = GoodputTracker()
        t.add("productive", 6.0)
        t.add("rollback", 1.0)
        t.add("checkpoint", 2.0)
        assert t.accounted_s() == pytest.approx(9.0)
        snap = t.snapshot()
        assert snap["productive_s"] == 6.0 and snap["rollback_s"] == 1.0
        # wall >= 0 and tiny here (clock started at first add)
        assert 0 <= snap["wall_s"] < 5.0
        with pytest.raises(ValueError, match="unknown goodput category"):
            t.add("coffee", 1.0)

    def test_measure_and_restart_window(self):
        t = GoodputTracker()
        with t.measure("eval"):
            pass
        t.mark_down()
        t.mark_up()
        t.mark_up()                          # idempotent: no open window
        assert t.buckets["eval"] >= 0
        assert t.buckets["restart"] >= 0
        assert t.goodput_fraction() == pytest.approx(
            t.buckets["productive"] / t.wall_s())

    def test_load_previous_accounts_downtime(self):
        import time
        t = GoodputTracker()
        t.load_previous({
            "goodput": {"productive_s": 5.0, "checkpoint_s": 1.0,
                        "wall_s": 7.0},
            "written_unix": time.time() - 3.0})
        assert t.buckets["productive"] == 5.0
        assert t.buckets["restart"] == pytest.approx(3.0, abs=0.5)
        assert t.wall_s() == pytest.approx(10.0, abs=0.5)

    def test_every_category_snapshots(self):
        snap = GoodputTracker().snapshot()
        for c in CATEGORIES:
            assert f"{c}_s" in snap


class TestNames:
    def test_validate(self):
        from dtf_tpu.telemetry.names import validate
        assert validate("checkpoint/save") == "checkpoint/save"
        for bad in ("CamelCase", "has space", "trailing/", "/leading",
                    "semi;colon"):
            with pytest.raises(ValueError):
                validate(bad)

    def test_source_tree_is_clean(self):
        """THE lint: every telemetry name literal in the package is
        scheme-shaped and declared in telemetry/names.py."""
        from dtf_tpu.telemetry.names import check_source_names
        root = os.path.join(os.path.dirname(__file__), "..", "dtf_tpu")
        paths = glob.glob(os.path.join(root, "**", "*.py"), recursive=True)
        assert paths
        assert check_source_names(paths) == []

    def test_wildcard_declarations(self):
        from dtf_tpu.telemetry.names import is_declared
        assert is_declared("health/step_ms_p3")
        assert is_declared("event/rollback")
        assert not is_declared("nonexistent/thing")


class TestMetricsCsvAttempts:
    def test_attempt_column_and_auto_resume(self, tmp_path):
        from dtf_tpu.train.metrics import MetricLogger
        d = str(tmp_path)
        lg = MetricLogger(d, attempt=0)
        lg.scalar(5, "cost", 2.0)
        lg.close()
        lg = MetricLogger(d, attempt=1)
        lg.scalar(5, "cost", 1.9)            # restart overlaps step 5
        lg.close()
        # attempt=None auto-continues past the file's last attempt
        lg = MetricLogger(d, attempt=None)
        assert lg.attempt == 2
        lg.scalar(10, "cost", 1.5)
        lg.close()
        rows = open(os.path.join(d, "metrics.csv")).read().splitlines()
        assert rows[0] == "step,metric,value,attempt"
        assert rows[1:] == ["5,cost,2.0,0", "5,cost,1.9,1", "10,cost,1.5,2"]

    def test_report_dedupes_latest_attempt(self):
        from dtf_tpu.telemetry.report import dedupe_latest_attempt
        rows = [(5, 0, "cost", 2.0), (10, 0, "cost", 1.95),
                (10, 1, "cost", 1.9), (15, 1, "cost", 1.7)]
        out = dedupe_latest_attempt(rows)
        assert (10, 1, "cost", 1.9) in out
        assert (10, 0, "cost", 1.95) not in out
        assert len(out) == 3

    def test_legacy_three_column_rows_read(self, tmp_path):
        from dtf_tpu.telemetry.report import load_metrics_csv
        p = tmp_path / "metrics.csv"
        p.write_text("step,metric,value\n5,cost,2.0\n7,cost,1.0\n")
        assert load_metrics_csv(str(p)) == [(5, 0, "cost", 2.0),
                                            (7, 0, "cost", 1.0)]


class TestSummarizeTraceSteps:
    def _write_trace(self, tmp_path):
        import gzip
        run = tmp_path / "plugins" / "profile" / "2026_01_01"
        run.mkdir(parents=True)
        events = [
            {"ph": "M", "pid": 3, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "X", "pid": 3, "tid": 1, "name": "fusion.1",
             "dur": 4_000_000},
        ]
        with gzip.open(run / "vm.trace.json.gz", "wt") as f:
            json.dump({"traceEvents": events}, f)

    def test_steps_normalizes_per_step(self, tmp_path):
        from dtf_tpu.utils.profiling import summarize_trace
        self._write_trace(tmp_path)
        assert summarize_trace(str(tmp_path)) == [("fusion.1", 4.0)]
        assert summarize_trace(str(tmp_path), steps=2) == [("fusion.1", 2.0)]

    def test_nonpositive_steps_rejected(self, tmp_path):
        from dtf_tpu.utils.profiling import summarize_trace
        self._write_trace(tmp_path)
        with pytest.raises(ValueError, match="positive traced-step"):
            summarize_trace(str(tmp_path), steps=0)


@pytest.mark.chaos
class TestGoodputUnderChaos:
    def _trainer(self, mesh8, cfg, chaos=None):
        from dtf_tpu import optim
        from dtf_tpu.cluster import Cluster
        from dtf_tpu.config import ClusterConfig
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import Trainer
        cluster = Cluster(config=ClusterConfig(), mesh=mesh8)
        return Trainer(cluster, MnistMLP(init_scale="fan_in"),
                       optim.sgd(0.05), cfg, chaos=chaos)

    def test_rollback_books_as_nonproductive(self, mesh8, tmp_path):
        """nan_grad x2 with bad_step_limit=2 forces a rollback restore:
        it must show up in the rollback bucket and the event counter, and
        the goodput columns must still sum to wall-clock."""
        from dtf_tpu.config import TrainConfig
        from dtf_tpu.data import load_mnist
        cfg = TrainConfig(batch_size=64, learning_rate=0.05, epochs=1,
                          log_frequency=1, seed=1, logdir=str(tmp_path),
                          checkpoint_every=2, bad_step_limit=2,
                          max_rollbacks=2,
                          chaos="nan_grad@3,nan_grad@4,stall@2:0.2s")
        t = self._trainer(mesh8, cfg)
        res = t.fit(load_mnist(seed=1), epochs=1, max_steps=8)
        t.logger.close()
        assert res["rollbacks"] == 1
        tracker = tel.get_tracker()
        assert tracker.buckets["rollback"] > 0
        assert tracker.buckets["stall"] >= 0.2
        assert tel.counter("event/rollback").value == 1
        assert tel.counter("chaos/faults_fired_total").value == 3
        doc = json.load(open(tmp_path / "telemetry.json"))
        g = doc["goodput"]
        total = sum(g[f"{c}_s"] for c in CATEGORIES)
        assert total == pytest.approx(g["wall_s"], rel=0.10)
        assert g["rollback_s"] > 0
        # chaos marks landed in the span timeline
        spans = read_spans(str(tmp_path / "spans.p0.jsonl"))
        marks = [r["name"] for r in spans if r["ph"] == "i"]
        assert "chaos/nan_grad" in marks and "chaos/stall" in marks

    def test_supervisor_restart_books_downtime(self):
        """A crash->restart cycle under run_supervised must land in the
        restart bucket (supervisor marks down, next attempt marks up)."""
        from dtf_tpu.resilience.supervisor import run_supervised
        from dtf_tpu.utils.retry import Backoff
        tracker = tel.get_tracker()

        def fit_once(attempt):
            if attempt == 0:
                raise OSError("injected crash")
            tracker.mark_up()              # the next Trainer's ctor does this
            return {"preempted": False}

        result = run_supervised(fit_once, max_restarts=1,
                                backoff=Backoff(base_s=0.05, max_s=0.05,
                                                jitter=0.0))
        assert result == {"preempted": False}
        assert tracker.buckets["restart"] >= 0.05
        assert tel.counter("supervisor/restarts_total").value == 1


class TestReportCLI:
    def _fixture_logdir(self, tmp_path):
        d = str(tmp_path)
        with open(os.path.join(d, "metrics.csv"), "w") as f:
            f.write("step,metric,value,attempt\n"
                    "5,cost,2.0,0\n10,cost,1.95,0\n"
                    "10,cost,1.9,1\n15,cost,1.7,1\n"
                    "10,event/rollback,1.0,1\n"
                    "15,health/step_ms_p0,12.0,1\n"
                    "15,health/step_ms_p1,30.0,1\n")
        with open(os.path.join(d, "telemetry.json"), "w") as f:
            json.dump({
                "goodput": {"productive_s": 8.0, "checkpoint_s": 0.6,
                            "rollback_s": 0.5, "restart_s": 0.5,
                            "stall_s": 0.2, "compile_s": 0.2,
                            "wall_s": 10.0, "accounted_s": 10.0,
                            "productive_fraction": 0.8},
                "metrics": {"throughput/tokens_per_s":
                            {"type": "gauge", "value": 1234.5},
                            "mfu/pct_peak":
                            {"type": "gauge", "value": 41.5}},
                "written_unix": 0}, f)
        tr = Tracer(os.path.join(d, "spans.p0.jsonl"), process=0)
        with tr.span("train/step"):
            pass
        tr.instant("chaos/host_down", step=30)
        tr.close()
        return d

    def test_golden_sections(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report
        d = self._fixture_logdir(tmp_path)
        assert report.main([d]) == 0
        out = capsys.readouterr().out
        # golden contract: the section lines the post-mortem reads
        assert f"== dtf_tpu run report: {d} ==" in out
        assert "Goodput breakdown" in out
        assert "goodput (productive/wall): 80.0%" in out
        assert "throughput/tokens_per_s            1234.5" in out
        assert "mfu/pct_peak                         41.5" in out
        assert ("Steps: 5..15  final cost 1.7000  (attempts: [0, 1], "
                "1 overlapping rows superseded by the latest attempt)"
                in out)
        assert "event/rollback (count 1)" in out
        assert "chaos/host_down" in out
        assert "p0: mean    12.00" in out and "p1: mean    30.00" in out
        assert "Top spans" in out and "train/step" in out

    def test_gradient_sync_section_golden(self, tmp_path, capsys):
        """The comm/* instruments render as a 'Gradient sync' section with
        the strategy index decoded back to its name (grad_sync.STRATEGIES
        order)."""
        import json as _json
        import os as _os

        from dtf_tpu.telemetry import report
        d = str(tmp_path)
        with open(_os.path.join(d, "telemetry.json"), "w") as f:
            _json.dump({
                "goodput": {"productive_s": 1.0, "wall_s": 1.0,
                            "accounted_s": 1.0},
                "metrics": {
                    "comm/strategy_idx": {"type": "gauge", "value": 1.0},
                    "comm/data_axis_size": {"type": "gauge", "value": 8.0},
                    "comm/bucket_count": {"type": "gauge", "value": 2.0},
                    "comm/grad_sync_bytes":
                        {"type": "gauge", "value": 636928.0},
                    "comm/optimizer_state_bytes":
                        {"type": "gauge", "value": 79620.0}},
                "written_unix": 0}, f)
        assert report.main([d]) == 0
        out = capsys.readouterr().out
        assert "Gradient sync" in out
        assert "strategy" in out and "zero1" in out
        assert "comm/optimizer_state_bytes" in out
        assert "79620" in out
        assert "comm/bucket_count" in out

    def test_check_gate(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report
        d = self._fixture_logdir(tmp_path)
        assert report.main([d, "--check"]) == 0
        assert "goodput check: OK" in capsys.readouterr().out
        # break the books: components no longer sum to wall
        doc = json.load(open(os.path.join(d, "telemetry.json")))
        doc["goodput"]["productive_s"] = 1.0
        json.dump(doc, open(os.path.join(d, "telemetry.json"), "w"))
        assert report.main([d, "--check"]) == 1
        assert "goodput check: FAIL" in capsys.readouterr().out

    def test_threshold_gates(self, tmp_path, capsys):
        """check_gates: the shared gate implementation behind the
        --min_goodput/--min_mfu/--max_rollbacks flags (and the scenario
        matrix runner).  Fixture: goodput 0.8, mfu 41.5%, tokens/s
        1234.5, final cost 1.7, no rollbacks counter."""
        from dtf_tpu.telemetry import report
        d = self._fixture_logdir(tmp_path)
        rep = report.build_report(d)
        ok, lines = report.check_gates(
            rep, min_goodput=0.5, min_mfu=40.0, max_rollbacks=1,
            min_tokens_per_s=1000.0, max_final_cost=2.0)
        assert ok, lines
        assert len(lines) == 5 and all("OK" in ln for ln in lines)
        # each bound individually violated flips only its own gate
        for kw, bad in (("min_goodput", 0.9), ("min_mfu", 50.0),
                        ("min_tokens_per_s", 2000.0),
                        ("max_final_cost", 1.0)):
            ok, lines = report.check_gates(rep, **{kw: bad})
            assert not ok and "FAIL" in lines[0], (kw, lines)
        # absent rollbacks counter reads as 0 (passes a ceiling of 0)
        ok, _ = report.check_gates(rep, max_rollbacks=0)
        assert ok
        # a gated-but-unmeasured quantity fails, never silently passes
        ok, lines = report.check_gates(rep, min_examples_per_s=1.0)
        assert not ok and "not measured" in lines[0]

    def test_wire_bytes_gate(self):
        """max_wire_bytes_per_step (ISSUE 19): ceiling on the per-step
        comm/wire_bytes gauge; a fatter wire fails, an absent gauge is
        not-measured = FAIL (a run that never recorded its wire cannot
        pass the wire gate)."""
        from dtf_tpu.telemetry import report
        rep = {"telemetry": {"metrics": {
            "comm/wire_bytes": {"value": 72800.0}}}}
        ok, lines = report.check_gates(rep,
                                       max_wire_bytes_per_step=76000.0)
        assert ok and "OK" in lines[0]
        ok, lines = report.check_gates(rep,
                                       max_wire_bytes_per_step=70000.0)
        assert not ok and "FAIL" in lines[0]
        ok, lines = report.check_gates({},
                                       max_wire_bytes_per_step=76000.0)
        assert not ok and "not measured" in lines[0]

    def test_threshold_gate_flags_imply_check(self, tmp_path, capsys):
        """The CLI flags arm the same gates and fail the exit code —
        without needing an explicit --check."""
        from dtf_tpu.telemetry import report
        d = self._fixture_logdir(tmp_path)
        assert report.main([d, "--min_goodput", "0.5", "--min_mfu", "40",
                            "--max_rollbacks", "0"]) == 0
        out = capsys.readouterr().out
        assert "gate min_goodput: OK" in out
        assert "gate min_mfu: OK" in out
        assert "gate max_rollbacks: OK" in out
        assert report.main([d, "--min_goodput", "0.95"]) == 1
        assert "gate min_goodput: FAIL" in capsys.readouterr().out

    def test_export_trace(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report
        d = self._fixture_logdir(tmp_path)
        out = os.path.join(d, "merged.json")
        assert report.main([d, "--export-trace", out]) == 0
        assert json.load(open(out))["traceEvents"]

    def test_json_mode(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report
        d = self._fixture_logdir(tmp_path)
        assert report.main([d, "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["attempts"] == [0, 1]
        assert doc["telemetry"]["goodput"]["wall_s"] == 10.0

    def test_empty_logdir(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report
        assert report.main([str(tmp_path)]) == 0
        assert "nothing found" in capsys.readouterr().out

    def test_missing_dir_rejected(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report
        assert report.main([str(tmp_path / "nope")]) == 2
