"""Multi-host failure-domain worker (spawned by tests/test_multiprocess.py
and resilience.supervisor.run_elastic_hosts).

One "host" of an N-host job with the health subsystem armed for real:
heartbeats in a shared rendezvous dir, chaos host faults targeted by
process index, the poison-pill coordinated abort, and checkpoint/resume on
a mesh sized by ``devices``.  The hosts form the health mesh EXPLICITLY
(process_index/num_processes passed in) rather than via
``jax.distributed`` — heartbeating, abort and elastic restart are
deliberately independent of the collective runtime (a dead peer's
collectives are exactly what you can no longer rely on), and this keeps
the scenario runnable on jaxlib builds whose CPU backend lacks
multiprocess collectives (where the rest of the rig skips).

Only host 0 owns the shared logdir/checkpoints (the survivor the elastic
supervisor relaunches); other hosts train a decoy replica in a scratch
logdir — their role is to heartbeat, straggle, partition, and die on cue.

Usage:
    _mp_health.py <task> <nproc> <shared_dir> <max_steps> <devices> [chaos]

Exits 0 on completion, 71/72 through the coordinated abort, or dies
outright under ``host_down``.  Host 0 prints
``MP_HEALTH_DONE steps=<n> final_cost=<loss>`` on completion.
"""

import os
import sys


def tiny_splits(n=1024, seed=0):
    """Deterministic, learnable 10-class data — identical on every host."""
    import numpy as np

    from dtf_tpu.data.datasets import Dataset, DataSplits

    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n)
    protos = rng.normal(0, 1, (10, 784)).astype(np.float32)
    x = (protos[y] + rng.normal(0, 2.0, (n, 784))).astype(np.float32)
    return DataSplits(train=Dataset(x, np.eye(10, dtype=np.float32)[y],
                                    seed=1), test=None)


def main(task: int, nproc: int, shared: str, max_steps: int,
         devices: int, chaos: str = "") -> int:
    from dtf_tpu import optim
    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.resilience.chaos import FaultPlan
    from dtf_tpu.resilience.health import HealthMonitor, make_transport
    from dtf_tpu.train.trainer import Trainer

    cluster = bootstrap(ClusterConfig(simulated_devices=devices,
                                      mesh="data=-1"))
    logdir = (os.path.join(shared, "logs") if task == 0
              else os.path.join(shared, f"logs_task{task}"))
    cfg = TrainConfig(
        batch_size=64, learning_rate=0.05, epochs=100,
        log_frequency=2, seed=1, logdir=logdir,
        checkpoint_every=5, resume=True)
    # The chaos plan and the health mesh carry THIS host's identity; one
    # spec string describes the whole cluster's failure schedule.
    plan = FaultPlan.parse(chaos, process_index=task) if chaos else None
    monitor = None
    if nproc > 1:
        monitor = HealthMonitor(
            make_transport(os.path.join(shared, "health"), task,
                           is_coordinator=task == 0),
            task, nproc, interval_s=0.25, miss_budget=4,
            boot_grace_s=120.0, is_coordinator=task == 0).start()
        if plan is not None:
            plan.bind_partition(monitor.partition)
    trainer = Trainer(cluster, MnistMLP(init_scale="fan_in"),
                      optim.sgd(0.05), cfg, chaos=plan)
    if monitor is not None:
        # Warm the step compile BEFORE the startup barrier, on a
        # throwaway state copy (step_fn donates its first argument) and a
        # dummy batch, so every host enters the fault schedule in
        # lockstep: compile skew must not let a fast host die before a
        # slow host has checkpointed anything.
        import jax
        import numpy as np

        from dtf_tpu.train.trainer import put_global_batch

        dummy = put_global_batch(
            cluster.mesh, (np.zeros((cfg.batch_size, 784), np.float32),
                           np.zeros((cfg.batch_size, 10), np.float32)))
        throwaway = jax.tree_util.tree_map(lambda x: x + 0, trainer.state)
        jax.block_until_ready(
            trainer.step_fn(throwaway, dummy, jax.random.key(0)))
        monitor.wait_for_peers(120.0)
    completed = False
    try:
        result = trainer.fit(tiny_splits(), max_steps=max_steps)
        completed = True
    finally:
        if monitor is not None:
            # Same protocol as the trainer's own close: only a COMPLETED
            # fit departs cleanly; a crash lets the beats stop so peers
            # run the coordinated abort.
            monitor.close(mark_departed=completed)
        if trainer.ckpt is not None:
            trainer.ckpt.close()
    if task == 0:
        print(f"MP_HEALTH_DONE steps={result['steps']} "
              f"final_cost={result['final_cost']:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                  int(sys.argv[4]), int(sys.argv[5]),
                  sys.argv[6] if len(sys.argv) > 6 else ""))
