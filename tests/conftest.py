"""Test rig: simulate an 8-device mesh on CPU.

The reference was untestable — hardcoded cluster IPs (tf_distributed.py:9-10)
meant it could not run outside its specific 6-8 host network, and it shipped
zero tests (SURVEY.md §4).  Here every distributed code path runs under
pytest on a single host via XLA's host-platform device-count simulation.
"""

import os

# Must be set before jax initializes its backends.  Note: this image's
# sitecustomize imports jax before conftest runs, so the JAX_PLATFORMS env
# var is already baked into jax.config — use config.update as well.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running integration tests")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8():
    from dtf_tpu.parallel.mesh import make_mesh
    return make_mesh("data=8")


@pytest.fixture()
def mesh_2d():
    from dtf_tpu.parallel.mesh import make_mesh
    return make_mesh("data=4,tensor=2")
