"""Test rig: simulate an 8-device mesh on CPU.

The reference was untestable — hardcoded cluster IPs (tf_distributed.py:9-10)
meant it could not run outside its specific 6-8 host network, and it shipped
zero tests (SURVEY.md §4).  Here every distributed code path runs under
pytest on a single host via XLA's host-platform device-count simulation.
"""

import os

# Must be set before jax initializes its backends.  Note: this image's
# sitecustomize imports jax before conftest runs, so the JAX_PLATFORMS env
# var is already baked into jax.config — use config.update as well.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process / long-running integration tests")
    config.addinivalue_line(
        "markers", "chaos: fault-injection / self-healing resilience tests")
    config.addinivalue_line(
        "markers", "serve: serving-engine tests (paged KV, scheduler, "
                   "load bench)")
    config.addinivalue_line(
        "markers", "scenarios: scenario-matrix tests (spec/zoo/runner/"
                   "CLI + real cells)")


# ---------------------------------------------------------------------------
# Fast-by-default test selection (VERDICT r2 weak #8): pytest.ini deselects
# `slow` tests so a fresh-image `pytest -q` finishes in minutes; the full
# ~40-minute suite runs with `pytest -m "slow or not slow"`.  Slowness is
# declared HERE, centrally, from a measured per-test duration log (>= ~7 s
# on the single-core CPU rig) rather than scattered pytestmark lines — to
# re-derive after a big change: `pytest --durations=0 -q`, then update.
# Matching is by nodeid prefix, so one entry can cover a parametrize set.
# ---------------------------------------------------------------------------

_SLOW_FILES = (
    "tests/test_multiprocess.py",        # spawns real worker processes
    "tests/test_process_data.py::TestTwoProcess",
    "tests/test_resnet.py",              # conv net epochs on CPU
    "tests/test_beam_search.py",         # exhaustive-search validation
    "tests/test_lm_workload.py",         # end-to-end CLI runs
    "tests/test_quantized_allreduce.py", # MNIST convergence A/B
)

_SLOW_TESTS = (
    "tests/test_bert.py::TestBert::test_dp_tp_train_step",
    "tests/test_bert.py::TestBert::test_fixed_k_loss_trains",
    "tests/test_bert.py::TestBert::test_loss_decreases",
    "tests/test_bert.py::TestBert::test_masking_respects_pad_mask",
    "tests/test_bert.py::TestBert::test_unrolled_layer_loop",
    "tests/test_bert_pretrain.py::TestBertPretrainCLI",
    "tests/test_bert_pretrain.py::TestRemat",
    "tests/test_checkpoint.py::TestTrainerResume::test_crash_resume",
    "tests/test_checkpoint.py::TestTrainerResume::test_resume_past",
    "tests/test_checkpoint.py::TestTrainerResume::test_second_fit",
    "tests/test_decode_kernel.py::TestFusedDecode::test_batched",
    "tests/test_decode_kernel.py::TestFusedDecode::test_batch16",
    "tests/test_decode_kernel.py::TestFusedDecode::test_batch32",
    "tests/test_decode_kernel.py::TestChunkedCache::test_composes",
    "tests/test_decode_kernel.py::TestChunkedCache::test_generate",
    "tests/test_gpt.py::TestShardedDecode::test_beam_tp_mesh",
    "tests/test_decode_kernel.py::TestFusedDecode::test_gqa_swiglu",
    "tests/test_decode_kernel.py::TestFusedDecode::test_greedy_matches",
    "tests/test_decode_kernel.py::TestFusedDecode::test_rope_llama",
    "tests/test_decode_kernel.py::TestFusedDecode::test_int8_fused",
    "tests/test_decode_kernel.py::TestFusedDecode::test_sampled_matches",
    "tests/test_gpt.py::TestGPTModel::test_1f1b_grads_match_dense_path",
    "tests/test_gpt.py::TestGPTModel::test_chunked_loss_matches_dense",
    "tests/test_gpt.py::TestGPTModel::test_remat_matches",
    "tests/test_gpt.py::TestGPTModel::test_unrolled_layer_loop",
    "tests/test_gpt.py::TestGPTModel::test_int8_decode",
    "tests/test_gpt.py::TestGPTModel::test_loss_decreases_in_training",
    "tests/test_gpt.py::TestGPTModel::test_pipelined_decoder_matches_scan",
    "tests/test_gpt.py::TestGenerateEdges",
    "tests/test_gpt.py::TestGeneration::test_greedy_matches_parallel",
    "tests/test_gpt.py::TestGeneration::test_sampling_deterministic",
    "tests/test_llama_style.py::TestLabelSmoothing",
    "tests/test_llama_style.py::TestLlamaStyleModel::test_greedy_decode",
    "tests/test_llama_style.py::TestLlamaStyleModel::test_remat_matches",
    "tests/test_llama_style.py::TestLlamaStyleModel::test_tensor_parallel",
    "tests/test_llama_style.py::TestLlamaStyleModel::test_trains",
    "tests/test_moe.py::TestMoE::test_balanced_router_aux_near_one",
    "tests/test_moe.py::TestMoE::test_capacity_drops_tokens",
    "tests/test_moe.py::TestMoE::test_collapsed_router",
    "tests/test_moe.py::TestMoE::test_expert_parallel_train_step",
    "tests/test_moe.py::TestMoE::test_gradients_flow_to_router",
    "tests/test_moe.py::TestMoE::test_matches_reference_with_ample",
    "tests/test_moe.py::TestMoE::test_moe_bert_trains_expert_parallel",
    "tests/test_optim.py::TestLamb::test_trains_bert_tiny",
    "tests/test_pipeline.py::Test1F1B::test_data_axis_composition",
    "tests/test_pipeline.py::Test1F1B::test_matches_unpipelined_grads",
    "tests/test_pipeline.py::TestBert1F1B",
    "tests/test_pipeline.py::TestPipeline::test_backward_pipeline_grads",
    "tests/test_pipeline.py::TestPipeline::test_composes_with_data_axis",
    "tests/test_pipeline.py::TestPipeline::test_ctx_routes",
    "tests/test_pipeline.py::TestPipeline::test_matches_sequential",
    "tests/test_preemption.py::TestPreemptedRun::test_sigterm_checkpoints",
    "tests/test_ring_attention.py::TestRingAttention::test_bf16",
    "tests/test_ring_attention.py::TestRingAttention::test_composes",
    "tests/test_ring_attention.py::TestRingAttention::test_grads_flow",
    "tests/test_ring_attention.py::TestRingAttention::test_impl_accepts",
    "tests/test_ring_attention.py::TestRingAttention"
    "::test_kv_mask_matches_full_attention",
    "tests/test_ring_attention.py::TestRingAttention"
    "::test_matches_full_attention",
    "tests/test_ring_attention.py::TestRingInMHA",
    "tests/test_sampling.py::TestGenerateIntegration",
    "tests/test_t5.py::Test1F1B",
    "tests/test_t5.py::TestGeneration::test_greedy_matches_teacher",
    "tests/test_t5.py::TestGeneration::test_sampling_deterministic",
    "tests/test_t5.py::TestPipelined",
    "tests/test_t5.py::TestTraining",
    "tests/test_trainer.py::TestGradAccumulation::test_stateful_model",
    "tests/test_trainer.py::TestTrainerEndToEnd::test_metrics_csv",
    "tests/test_ulysses_attention.py::TestUlyssesAttention::test_bf16",
    "tests/test_ulysses_attention.py::TestUlyssesAttention::test_grads",
    "tests/test_ulysses_attention.py::TestUlyssesAttention::test_impl",
    "tests/test_ulysses_attention.py::TestUlyssesAttention"
    "::test_matches_full_attention",
    "tests/test_ulysses_attention.py::TestUlyssesInModels",
    "tests/test_fleet.py::TestFleetTwoProcess",  # spawns 2 real hosts
)


def pytest_collection_modifyitems(config, items):
    prefixes = _SLOW_FILES + _SLOW_TESTS
    for item in items:
        nodeid = item.nodeid.replace("\\", "/")
        if not nodeid.startswith("tests/"):
            nodeid = "tests/" + nodeid
        if any(nodeid.startswith(p) for p in prefixes):
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs


@pytest.fixture()
def mesh8():
    from dtf_tpu.parallel.mesh import make_mesh
    return make_mesh("data=8")


@pytest.fixture()
def mesh_2d():
    from dtf_tpu.parallel.mesh import make_mesh
    return make_mesh("data=4,tensor=2")
