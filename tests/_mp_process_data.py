"""Child script for test_process_data.py: 2-process job where each host
feeds only ITS OWN slice of a deterministic global batch through
put_process_batch, then runs one explicit-mode train step and prints the
loss (which must equal the single-process full-batch loss)."""

import sys

import jax
import numpy as np


def main() -> int:
    task, coord = int(sys.argv[1]), sys.argv[2]
    jax.distributed.initialize(coordinator_address=coord, num_processes=2,
                               process_id=task)

    from dtf_tpu import optim
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.parallel.mesh import make_mesh
    from dtf_tpu.train.trainer import (init_state, make_train_step,
                                       put_process_batch)

    mesh = make_mesh("data=-1")
    model = MnistMLP(init_scale="fan_in")
    opt = optim.sgd(0.1)
    state = init_state(model, opt, seed=1, mesh=mesh)
    step = make_train_step(model.loss, opt, mesh, mode="explicit",
                           donate=False)

    # deterministic GLOBAL batch; this process materializes only its slice
    rng = np.random.default_rng(42)
    gx = rng.random((32, 784), np.float32)
    gy = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)]
    lo, hi = task * 16, (task + 1) * 16
    batch = put_process_batch(mesh, (gx[lo:hi], gy[lo:hi]))

    state, m = step(state, batch, jax.random.key(0))
    print(f"LOSS={float(m['loss']):.10f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
