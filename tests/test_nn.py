"""NN library + optimizer unit tests (SURVEY.md §4: model fwd/loss numerics
vs closed form, reference math at tf_distributed.py:60-70)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu.nn import (
    BatchNorm, Conv2D, Dense, Dropout, Embedding, LayerNorm, Sequential,
    accuracy, naive_cross_entropy, softmax_cross_entropy,
)
from dtf_tpu.models.mlp import MnistMLP


class TestLayers:
    def test_dense_matches_closed_form(self):
        d = Dense(4, 3)
        p = d.init(jax.random.key(0))
        x = jnp.ones((2, 4))
        np.testing.assert_allclose(d.apply(p, x),
                                   x @ p["w"] + p["b"], rtol=1e-6)

    def test_dense_reference_init_is_unit_normal(self):
        d = Dense(784, 100, init_scale="reference")
        p = d.init(jax.random.key(1))
        assert abs(float(jnp.std(p["w"])) - 1.0) < 0.02   # tf.random_normal stddev 1
        assert float(jnp.abs(p["b"]).max()) == 0.0        # zeros, :55-57

    def test_layernorm_normalizes(self):
        ln = LayerNorm(16)
        p = ln.init(jax.random.key(0))
        y = ln.apply(p, jax.random.normal(jax.random.key(1), (4, 16)) * 5 + 3)
        np.testing.assert_allclose(np.mean(np.asarray(y), -1), 0.0, atol=1e-4)
        np.testing.assert_allclose(np.std(np.asarray(y), -1), 1.0, atol=1e-2)

    def test_conv_shape(self):
        c = Conv2D(3, 8, (3, 3), strides=(2, 2))
        p = c.init(jax.random.key(0))
        assert c.apply(p, jnp.zeros((2, 32, 32, 3))).shape == (2, 16, 16, 8)

    def test_batchnorm_train_stats(self):
        bn = BatchNorm(4)
        p, s = bn.init(jax.random.key(0)), bn.init_state()
        x = jax.random.normal(jax.random.key(1), (64, 4)) * 3 + 1
        y, s2 = bn.apply_stateful(p, s, x, train=True)
        np.testing.assert_allclose(np.mean(np.asarray(y), 0), 0.0, atol=1e-4)
        assert not np.allclose(s2["mean"], s["mean"])   # stats moved

    def test_dropout_train_vs_eval(self):
        dr = Dropout(0.5)
        x = jnp.ones((1000,))
        y = dr.apply({}, x, train=True, rng=jax.random.key(0))
        assert float(jnp.mean(y == 0)) == pytest.approx(0.5, abs=0.1)
        np.testing.assert_array_equal(dr.apply({}, x, train=False), x)

    def test_embedding_lookup(self):
        e = Embedding(10, 4)
        p = e.init(jax.random.key(0))
        out = e.apply(p, jnp.array([1, 5]))
        np.testing.assert_array_equal(out, p["table"][jnp.array([1, 5])])

    def test_sequential_composes_and_axes(self):
        m = Sequential([Dense(4, 8), jax.nn.relu, Dense(8, 2, axes_in="mlp",
                                                        axes_out="embed")])
        p = m.init(jax.random.key(0))
        assert m.apply(p, jnp.ones((1, 4))).shape == (1, 2)
        ax = m.axes()
        assert ax["0"]["w"] == ("embed", "mlp")
        assert ax["2"]["w"] == ("mlp", "embed")


class TestLosses:
    def test_stable_xent_matches_naive_where_stable(self):
        logits = jax.random.normal(jax.random.key(0), (8, 10))
        y = jax.nn.one_hot(jnp.arange(8) % 10, 10)
        stable = softmax_cross_entropy(logits, y, reduction="sum")
        naive = naive_cross_entropy(jax.nn.softmax(logits), y)
        np.testing.assert_allclose(float(stable), float(naive), rtol=1e-5)

    def test_stable_xent_survives_extreme_logits(self):
        """The reference formula (tf_distributed.py:70) produces inf here."""
        logits = jnp.array([[1000.0, -1000.0]])
        y = jnp.array([[0.0, 1.0]])
        naive = naive_cross_entropy(jax.nn.softmax(logits), y)
        assert not bool(jnp.isfinite(naive))        # reference math: inf
        assert bool(jnp.isfinite(softmax_cross_entropy(logits, y)))

    def test_accuracy(self):
        logits = jnp.array([[2.0, 1.0], [0.0, 3.0], [5.0, 0.0]])
        y = jnp.array([[1.0, 0.0], [0.0, 1.0], [0.0, 1.0]])
        assert float(accuracy(logits, y)) == pytest.approx(2 / 3)


class TestOptim:
    def test_sgd_matches_reference_update_rule(self):
        """w -= lr*g, the reference's GradientDescentOptimizer
        (tf_distributed.py:73-76)."""
        opt = optim.sgd(0.0005)
        params = {"w": jnp.ones((3,))}
        grads = {"w": jnp.full((3,), 2.0)}
        upd, _ = opt.update(grads, opt.init(params), params)
        new = optim.apply_updates(params, upd)
        np.testing.assert_allclose(new["w"], 1.0 - 0.0005 * 2.0, rtol=1e-6)

    def test_momentum(self):
        opt = optim.momentum(0.1, beta=0.9)
        p = {"w": jnp.zeros(())}
        g = {"w": jnp.ones(())}
        s = opt.init(p)
        u1, s = opt.update(g, s, p)
        u2, s = opt.update(g, s, p)
        assert float(u2["w"]) == pytest.approx(-0.1 * 1.9)

    def test_adam_step_direction(self):
        opt = optim.adam(1e-3)
        p = {"w": jnp.zeros((2,))}
        g = {"w": jnp.array([1.0, -1.0])}
        s = opt.init(p)
        u, s = opt.update(g, s, p)
        # First Adam step is ~ -lr * sign(g).
        np.testing.assert_allclose(np.asarray(u["w"]), [-1e-3, 1e-3], rtol=1e-3)

    def test_clip_by_global_norm(self):
        opt = optim.clip_by_global_norm(optim.sgd(1.0), 1.0)
        g = {"w": jnp.array([3.0, 4.0])}   # norm 5
        u, _ = opt.update(g, (), None)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(u["w"])), 1.0,
                                   rtol=1e-5)

    def test_warmup_cosine_schedule(self):
        sched = optim.warmup_cosine(1.0, 10, 110)
        assert float(sched(jnp.asarray(5))) == pytest.approx(0.5)
        assert float(sched(jnp.asarray(10))) == pytest.approx(1.0, abs=1e-3)
        assert float(sched(jnp.asarray(110))) == pytest.approx(0.0, abs=1e-6)


class TestMnistMLP:
    def test_forward_shapes_and_seed1_determinism(self):
        m = MnistMLP()
        p1 = m.init(jax.random.key(1))
        p2 = m.init(jax.random.key(1))
        x = jnp.zeros((5, 784))
        assert m.apply(p1, x).shape == (5, 10)
        np.testing.assert_array_equal(p1["l1"]["w"], p2["l1"]["w"])

    def test_loss_returns_aux(self):
        m = MnistMLP(init_scale="fan_in")
        p = m.init(jax.random.key(1))
        x = jax.random.uniform(jax.random.key(0), (4, 784))
        y = jax.nn.one_hot(jnp.arange(4) % 10, 10)
        loss, aux = m.loss(p, (x, y))
        assert jnp.isfinite(loss)
        assert set(aux) == {"accuracy", "naive_cost"}
