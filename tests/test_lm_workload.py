"""GPT LM workload CLI: pretrain benchmark + generation demo."""

import pytest

from dtf_tpu.workloads.lm import main


class TestLMWorkload:
    def test_runs_with_generation(self, tmp_path, capsys):
        rc = main(["--preset", "tiny", "--steps", "4", "--batch_size", "16",
                   "--mesh", "data=4,fsdp=2", "--log_frequency", "2",
                   "--generate", "8", "--logdir", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Step-Time:" in out
        assert "Perplexity:" in out
        assert "Generated:" in out
        assert "done" in out

    def test_xla_attn_flag(self, tmp_path, capsys):
        rc = main(["--preset", "tiny", "--steps", "2", "--batch_size", "8",
                   "--attn", "xla", "--log_frequency", "2",
                   "--logdir", str(tmp_path)])
        assert rc == 0
        assert "Step-Time:" in capsys.readouterr().out

    def test_checkpoint_resume_continues_run(self, tmp_path, capsys):
        """The LM benchmark now runs on the ONE Trainer loop, so it
        checkpoints and resumes mid-run like every other workload: a second
        invocation with --resume restores the saved step and continues to
        the (larger) step budget instead of restarting from zero."""
        args = ["--preset", "tiny", "--batch_size", "8",
                "--log_frequency", "2", "--checkpoint_every", "2",
                "--logdir", str(tmp_path)]
        rc = main(args + ["--steps", "4"])
        assert rc == 0
        first = capsys.readouterr().out
        # budget = steps + 2 warmup = 6 optimizer steps, final save forced
        assert "Step: 6" in first

        rc = main(args + ["--steps", "8", "--resume"])
        assert rc == 0
        second = capsys.readouterr().out
        assert "resumed from step 6" in second
        assert "Step: 10" in second          # continued 6 -> 10, not 0 -> 10
        assert "Step: 2" not in second       # no replay of early steps
