"""GPT LM workload CLI: pretrain benchmark + generation demo."""

import pytest

from dtf_tpu.workloads.lm import main


class TestLMWorkload:
    def test_runs_with_generation(self, tmp_path, capsys):
        rc = main(["--preset", "tiny", "--steps", "4", "--batch_size", "16",
                   "--mesh", "data=4,fsdp=2", "--log_frequency", "2",
                   "--generate", "8", "--logdir", str(tmp_path)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Step-Time:" in out
        assert "Perplexity:" in out
        assert "Generated:" in out
        assert "done" in out

    def test_xla_attn_flag(self, tmp_path, capsys):
        rc = main(["--preset", "tiny", "--steps", "2", "--batch_size", "8",
                   "--attn", "xla", "--log_frequency", "2",
                   "--logdir", str(tmp_path)])
        assert rc == 0
        assert "Step-Time:" in capsys.readouterr().out
