"""Multi-host failure domain (resilience/health.py) — unit level.

Every piece of the heartbeat / coordinated-abort / elastic-restart
machinery runs in-process here with injected clocks and abort hooks;
tests/test_multiprocess.py drives the same code across real processes.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from dtf_tpu.resilience.health import (
    DEPARTED, EXIT_PEER_LOST, EXIT_SELF_ISOLATED, FileHeartbeatTransport,
    HealthMonitor, TcpHeartbeatTransport, flag_stragglers, make_transport,
)
from dtf_tpu.resilience.supervisor import (
    SupervisorGaveUp, classify_exit, run_elastic_hosts, run_supervised,
)

pytestmark = pytest.mark.chaos


def wait_for(predicate, timeout_s=10.0, poll_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return predicate()


class TestStragglerPolicy:
    def test_flags_slower_than_median_factor(self):
        assert flag_stragglers([10.0, 10.0, 25.0, 10.0], 2.0) == [2]

    def test_factor_at_most_one_disables(self):
        assert flag_stragglers([10.0, 1000.0], 1.0) == []
        assert flag_stragglers([10.0, 1000.0], 0.0) == []

    def test_single_host_never_flags(self):
        assert flag_stragglers([999.0], 2.0) == []

    def test_median_not_mean(self):
        """One dying host must not drag the baseline up and mask itself
        (mean of [10,10,10,1000] is 257 — a 2x-mean rule would miss a
        500ms host; the median rule does not)."""
        assert flag_stragglers([10.0, 10.0, 10.0, 500.0], 2.0) == [3]

    def test_nonfinite_timing_is_flagged(self):
        assert flag_stragglers([float("nan"), 10.0, 10.0], 1.5) == [0]


class TestFileTransport:
    def test_beat_roundtrip_and_departed(self, tmp_path):
        a = FileHeartbeatTransport(str(tmp_path), 0)
        b = FileHeartbeatTransport(str(tmp_path), 1)
        a.beat(3)
        b.beat(7)
        assert a.read_beats() == {0: 3, 1: 7}
        b.beat(DEPARTED)
        assert a.read_beats()[1] == DEPARTED

    def test_poison_plant_and_overwrite(self, tmp_path):
        """Planting overwrites: a pill left by a previous elastic round
        (which relaunched monitors ignore by identity) must not block
        this round's verdict."""
        t = FileHeartbeatTransport(str(tmp_path), 0)
        assert t.read_poison() is None
        t.plant_poison("peer 1 missed budget", source=0)
        assert t.read_poison()["source"] == 0
        t.plant_poison("this round's verdict", source=1)
        p = t.read_poison()
        assert p["reason"] == "this round's verdict" and p["source"] == 1

    def test_beat_returns_poison(self, tmp_path):
        t = FileHeartbeatTransport(str(tmp_path), 0)
        assert t.beat(1) is None
        t.plant_poison("why", source=1)
        assert t.beat(2)["reason"] == "why"

    def test_make_transport_selects_scheme(self, tmp_path):
        t = make_transport(str(tmp_path / "hb"), 0, True)
        assert isinstance(t, FileHeartbeatTransport)
        t2 = make_transport("tcp://127.0.0.1:0", 0, True)
        assert isinstance(t2, TcpHeartbeatTransport)
        t2.close()


class TestTcpTransport:
    def test_beat_and_poison_over_socket(self):
        coord = TcpHeartbeatTransport("127.0.0.1:0", 0, True)
        try:
            addr = "127.0.0.1:%d" % coord._server.address[1]
            client = make_transport(f"tcp://{addr}", 1, False)
            assert client.beat(1) is None
            assert coord.read_beats() == {1: 1}
            assert not client.observes_peers and coord.observes_peers
            coord.plant_poison("host 2 missed budget", source=0)
            assert client.beat(2)["reason"] == "host 2 missed budget"
            assert client.read_poison()["source"] == 0
        finally:
            coord.close()

    def test_client_can_plant_poison(self):
        coord = TcpHeartbeatTransport("127.0.0.1:0", 0, True)
        try:
            addr = "127.0.0.1:%d" % coord._server.address[1]
            client = make_transport(f"tcp://{addr}", 1, False)
            client.plant_poison("I saw it first", source=1)
            assert coord.read_poison()["reason"] == "I saw it first"
        finally:
            coord.close()

    def test_unreachable_coordinator_counts_failures(self):
        client = TcpHeartbeatTransport("127.0.0.1:1", 1, False)
        client.beat(1)
        client.beat(2)
        assert client.consecutive_failures == 2

    def test_malformed_requests_do_not_kill_the_server(self):
        """A port scanner / HTTP probe / buggy client must get an err
        reply, not kill the serve thread (a dead beat sink would read as
        a dead coordinator and self-isolate every healthy client)."""
        import socket

        coord = TcpHeartbeatTransport("127.0.0.1:0", 0, True)
        try:
            addr = coord._server.address

            def raw(line):
                with socket.create_connection(addr, timeout=2) as c:
                    c.sendall((line + "\n").encode())
                    return c.makefile("r").readline().strip()

            assert raw("beat notanint alsonot").startswith("err")
            assert raw("GET / HTTP/1.1").startswith("err")
            assert raw("poison }{garbage").startswith("err")
            # the server is still alive and serving real beats
            client = make_transport(
                "tcp://127.0.0.1:%d" % addr[1], 1, False)
            assert client.beat(1) is None
            assert coord.read_beats() == {1: 1}
        finally:
            coord.close()


class _Recorder:
    """Injected abort hook: records instead of os._exit."""

    def __init__(self):
        self.calls = []

    def __call__(self, code, reason):
        self.calls.append((code, reason))


def _monitor(tmp_path, pid, nproc, recorder, **kw):
    kw.setdefault("interval_s", 0.05)
    kw.setdefault("miss_budget", 3)
    kw.setdefault("boot_grace_s", 1.0)
    return HealthMonitor(FileHeartbeatTransport(str(tmp_path), pid),
                         pid, nproc, on_abort=recorder,
                         print_fn=lambda msg: None, **kw)


class TestHealthMonitor:
    def test_dead_peer_plants_poison_and_aborts(self, tmp_path):
        """A peer whose beats stop past the miss budget: the observer
        plants the pill and exits EXIT_PEER_LOST — the no-more-hanging-
        in-psum guarantee."""
        rec0, rec1 = _Recorder(), _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0).start()
        m1 = _monitor(tmp_path, 1, 2, rec1).start()
        try:
            time.sleep(0.4)
            assert not rec0.calls and not rec1.calls   # both healthy
            m1._stop.set()                             # abrupt death: no
            m1._thread.join()                          # DEPARTED written
            assert wait_for(lambda: rec0.calls), "no abort"
            code, reason = rec0.calls[0]
            assert code == EXIT_PEER_LOST
            assert "missed" in reason
            assert m0.aborted == reason
            poison = json.load(open(tmp_path / "poison.json"))
            assert poison["source"] == 0
        finally:
            m0._stop.set()
            m1._stop.set()

    def test_clean_departure_is_not_death(self, tmp_path):
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0).start()
        m1 = _monitor(tmp_path, 1, 2, _Recorder()).start()
        try:
            time.sleep(0.3)
            m1.close()                                 # writes DEPARTED
            time.sleep(0.6)
            assert not rec0.calls, rec0.calls
        finally:
            m0._stop.set()

    def test_poison_pill_aborts_observers(self, tmp_path):
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0).start()
        try:
            FileHeartbeatTransport(str(tmp_path), 1).plant_poison(
                "process 2 lost", source=1)
            assert wait_for(lambda: rec0.calls)
            assert rec0.calls[0][0] == EXIT_PEER_LOST
            assert "poison" in rec0.calls[0][1]
        finally:
            m0._stop.set()

    def test_own_poison_does_not_reabort(self, tmp_path):
        """The planter already aborted once; seeing its own pill on a
        later loop must not double-fire (source check)."""
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0)
        m0.transport.plant_poison("mine", source=0)
        m0.start()
        time.sleep(0.3)
        m0._stop.set()
        assert all("mine" not in r for _, r in rec0.calls)

    def test_coordinator_publishes_snapshot(self, tmp_path):
        m0 = _monitor(tmp_path, 0, 2, _Recorder()).start()
        m1 = _monitor(tmp_path, 1, 2, _Recorder()).start()
        try:
            assert wait_for(
                lambda: os.path.exists(tmp_path / "health.json"))
            snap = json.load(open(tmp_path / "health.json"))
            assert snap["coordinator"] == 0
            assert set(snap["processes"]) == {"0", "1"}
            assert snap["miss_budget"] == 3
        finally:
            m0._stop.set()
            m1._stop.set()

    def test_partitioned_host_self_isolates(self, tmp_path):
        """partition@S semantics: the cut-off side exits
        EXIT_SELF_ISOLATED (never mistaken for a survivor), the majority
        side plants the pill and exits EXIT_PEER_LOST."""
        rec0, rec1 = _Recorder(), _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0).start()
        m1 = _monitor(tmp_path, 1, 2, rec1).start()
        try:
            time.sleep(0.3)
            m1.partition()
            assert wait_for(lambda: rec0.calls and rec1.calls)
            assert rec1.calls[0][0] == EXIT_SELF_ISOLATED
            assert rec0.calls[0][0] == EXIT_PEER_LOST
        finally:
            m0._stop.set()
            m1._stop.set()

    def test_all_peers_quiet_means_self_isolated(self, tmp_path):
        """>= 2 independent peers all going quiet at once: the observer
        concludes IT is the partitioned one (exit 72, not 71)."""
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 3, rec0, boot_grace_s=0.2).start()
        try:
            assert wait_for(lambda: rec0.calls)
            assert rec0.calls[0][0] == EXIT_SELF_ISOLATED
        finally:
            m0._stop.set()

    def test_single_peer_quiet_is_peer_lost(self, tmp_path):
        """With ONE peer the evidence is symmetric — default to survivor
        semantics (71) so a 2-host job's healthy half elastically
        restarts."""
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0, boot_grace_s=0.2).start()
        try:
            assert wait_for(lambda: rec0.calls)
            assert rec0.calls[0][0] == EXIT_PEER_LOST
        finally:
            m0._stop.set()

    def test_stale_pill_from_previous_round_is_ignored(self, tmp_path):
        """Elastic relaunch over the same rendezvous dir: the previous
        round's pill must not abort the new round on arrival — but a NEW
        pill must still fire."""
        FileHeartbeatTransport(str(tmp_path), 9).plant_poison(
            "last round's casualty", source=9)
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0).start()
        m1 = _monitor(tmp_path, 1, 2, _Recorder()).start()
        try:
            time.sleep(0.4)
            assert not rec0.calls, rec0.calls      # stale pill ignored
            m0.transport.plant_poison("fresh verdict", source=1)
            assert wait_for(lambda: rec0.calls)
            assert "fresh verdict" in rec0.calls[0][1]
        finally:
            m0._stop.set()
            m1._stop.set()

    def test_departed_unlatches_for_reused_slot(self, tmp_path):
        """After an elastic relaunch a slot's beat file may still hold the
        previous owner's DEPARTED marker; fresh beats must resurrect the
        slot — and its later death must be detected again."""
        FileHeartbeatTransport(str(tmp_path), 1).beat(DEPARTED)
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0).start()
        m1 = _monitor(tmp_path, 1, 2, _Recorder()).start()
        try:
            time.sleep(0.4)
            assert not rec0.calls                  # peer 1 alive again
            m1._stop.set()                         # abrupt death
            m1._thread.join()
            assert wait_for(lambda: rec0.calls), \
                "DEPARTED latch masked a real death"
            assert rec0.calls[0][0] == EXIT_PEER_LOST
        finally:
            m0._stop.set()

    def test_crash_close_does_not_mark_departed(self, tmp_path):
        """fit's crash path closes with mark_departed=False: the beats
        just stop, and the peers' abort protocol (correctly) fires."""
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0).start()
        m1 = _monitor(tmp_path, 1, 2, _Recorder()).start()
        try:
            time.sleep(0.3)
            m1.close(mark_departed=False)          # crashed, not done
            assert wait_for(lambda: rec0.calls)
            assert rec0.calls[0][0] == EXIT_PEER_LOST
        finally:
            m0._stop.set()

    def test_wait_for_peers_barrier(self, tmp_path):
        """Startup rendezvous: returns once every peer has beaten, times
        out (False) when one never shows."""
        m0 = _monitor(tmp_path, 0, 2, _Recorder()).start()
        try:
            assert not m0.wait_for_peers(timeout_s=0.3)   # peer absent
            m1 = _monitor(tmp_path, 1, 2, _Recorder()).start()
            try:
                assert m0.wait_for_peers(timeout_s=10.0)
                assert m1.wait_for_peers(timeout_s=10.0)
            finally:
                m1._stop.set()
        finally:
            m0._stop.set()

    def test_straggler_feed_reaches_metrics(self, tmp_path):
        """The trainer's sync-point feed: per-host step times land as
        health/ scalars in metrics.csv, flagged hosts get a console
        line."""
        from dtf_tpu.train.metrics import MetricLogger

        logger = MetricLogger(str(tmp_path / "logs"), is_coordinator=True,
                              quiet=True)
        logger.stragglers(7, [10.0, 30.0], flagged=[1])
        logger.close()
        rows = open(tmp_path / "logs" / "metrics.csv").read()
        assert "health/step_ms_p0" in rows and "health/step_ms_p1" in rows
        assert "health/stragglers" in rows

    def test_boot_grace_covers_slow_starters(self, tmp_path):
        rec0 = _Recorder()
        m0 = _monitor(tmp_path, 0, 2, rec0, boot_grace_s=10.0).start()
        try:
            time.sleep(0.5)      # way past miss budget, inside boot grace
            assert not rec0.calls
        finally:
            m0._stop.set()


class TestExitClassification:
    def test_classify(self):
        from dtf_tpu.train.checkpoint import CheckpointMismatchError
        from dtf_tpu.train.trainer import TrainingDiverged

        assert classify_exit(TrainingDiverged("nan storm")) == "terminal"
        assert classify_exit(CheckpointMismatchError("x")) == "terminal"
        assert classify_exit(RuntimeError("transient")) == "retryable"
        flagged = RuntimeError("refused resume")
        flagged.no_restart = True
        assert classify_exit(flagged) == "terminal"

    def test_training_diverged_does_not_burn_restarts(self):
        """The unwinnable-loop fix: a deterministic divergence fails fast
        on attempt 0 instead of replaying through the whole budget."""
        from dtf_tpu.train.trainer import TrainingDiverged

        calls = []

        def fit_once(attempt):
            calls.append(attempt)
            raise TrainingDiverged("persists across rollbacks")

        with pytest.raises(TrainingDiverged):
            run_supervised(fit_once, max_restarts=5, sleep=lambda s: None)
        assert calls == [0]


def _exit_cmd(code):
    return [sys.executable, "-c", f"import sys; sys.exit({code})"]


class TestElasticHosts:
    def test_completes_on_survivors_after_host_loss(self):
        """Round 0: slot 1 dies (rc 9), slot 0 coordinated-aborts (71).
        Round 1 relaunches ONLY the survivor, reindexed to slot 0, and
        completes."""
        rounds = []

        def build_cmd(slot, n_hosts, round_idx):
            rounds.append((round_idx, slot, n_hosts))
            if round_idx == 0:
                return _exit_cmd(9 if slot == 1 else EXIT_PEER_LOST)
            return _exit_cmd(0)

        outs, n_final, used = run_elastic_hosts(build_cmd, 2, max_rounds=2)
        assert (n_final, used) == (1, 1)
        assert len(outs) == 1
        assert rounds == [(0, 0, 2), (0, 1, 2), (1, 0, 1)]

    def test_self_isolated_host_is_not_a_survivor(self):
        """Exit 72 (partitioned side) must be excluded from the relaunch
        set — only 71/0 count."""
        seen = []

        def build_cmd(slot, n_hosts, round_idx):
            seen.append((round_idx, n_hosts))
            if round_idx == 0:
                return _exit_cmd(EXIT_SELF_ISOLATED if slot == 2
                                 else EXIT_PEER_LOST)
            return _exit_cmd(0)

        outs, n_final, used = run_elastic_hosts(build_cmd, 3, max_rounds=1)
        assert (n_final, used) == (2, 1)
        assert (1, 2) in seen

    def test_gives_up_when_rounds_exhausted(self):
        def build_cmd(slot, n_hosts, round_idx):
            return _exit_cmd(9 if slot == n_hosts - 1 else EXIT_PEER_LOST)

        with pytest.raises(SupervisorGaveUp) as ei:
            run_elastic_hosts(build_cmd, 3, max_rounds=1)
        assert len(ei.value.history) == 2

    def test_gives_up_when_no_survivors(self):
        def build_cmd(slot, n_hosts, round_idx):
            return _exit_cmd(9)

        with pytest.raises(SupervisorGaveUp):
            run_elastic_hosts(build_cmd, 2, max_rounds=5)

    def test_hung_host_is_killed_and_counted_dead(self):
        def build_cmd(slot, n_hosts, round_idx):
            if round_idx == 0 and slot == 1:
                return [sys.executable, "-c",
                        "import time; time.sleep(600)"]
            return _exit_cmd(EXIT_PEER_LOST if round_idx == 0 else 0)

        outs, n_final, used = run_elastic_hosts(
            build_cmd, 2, max_rounds=1, timeout_s=3.0)
        assert (n_final, used) == (1, 1)
        assert "killed" in outs[0] or n_final == 1


class TestPreemptionExtensions:
    def test_sigint_optional(self):
        from dtf_tpu.utils.preemption import PreemptionHandler

        assert PreemptionHandler.signals_for(False) == (signal.SIGTERM,)
        assert PreemptionHandler.signals_for(True) == (signal.SIGTERM,
                                                       signal.SIGINT)
        h = PreemptionHandler(signals=PreemptionHandler.signals_for(True))
        try:
            assert h.trigger_count == 0
            os.kill(os.getpid(), signal.SIGINT)
            time.sleep(0.05)
            assert h.triggered and h.trigger_count == 1
            assert h.received == [signal.SIGINT]
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.05)
            assert h.trigger_count == 2
        finally:
            h.restore()


class TestMeshShrink:
    def test_shrinks_data_axis(self):
        from dtf_tpu.parallel.mesh import shrink_to_devices

        spec = shrink_to_devices("data=8", 4)
        assert spec.sizes == (4,)
        spec = shrink_to_devices("data=4,tensor=2", 4)
        assert spec.names == ("data", "tensor") and spec.sizes == (2, 2)

    def test_inferred_axis_unchanged(self):
        from dtf_tpu.parallel.mesh import shrink_to_devices

        assert shrink_to_devices("data=-1", 3).sizes == (-1,)

    def test_model_axes_never_degrade(self):
        from dtf_tpu.parallel.mesh import shrink_to_devices

        with pytest.raises(ValueError, match="model axes"):
            shrink_to_devices("data=4,tensor=2", 3)
        with pytest.raises(ValueError, match="no data axis"):
            shrink_to_devices("tensor=4", 2)

    def test_bootstrap_elastic_refits_fixed_mesh(self):
        """--elastic: a fixed data=16 spec sized for the pre-failure
        cluster re-fits onto this rig's 8 simulated devices."""
        from dtf_tpu.cluster import bootstrap
        from dtf_tpu.config import ClusterConfig

        cluster = bootstrap(ClusterConfig(mesh="data=16", elastic=True))
        assert cluster.mesh.shape["data"] == 8

    def test_manifest_records_writer_nproc(self, tmp_path, mesh8):
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.checkpoint import CheckpointManager
        from dtf_tpu.train.trainer import init_state

        state = init_state(MnistMLP(init_scale="fan_in"), optim.sgd(0.1),
                           seed=1, mesh=mesh8)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(5, state, force=True)
        mgr.wait()
        assert mgr.manifest_meta(5)["nproc"] == 1
        mgr.close()


class TestClusterStartHealth:
    def test_single_process_returns_none(self):
        from dtf_tpu.cluster import Cluster
        from dtf_tpu.config import ClusterConfig
        from dtf_tpu.parallel.mesh import make_mesh

        c = Cluster(config=ClusterConfig(hb_interval_s=0.5,
                                         health_dir="/tmp/x"),
                    mesh=make_mesh("data=8"))
        assert c.start_health() is None

    def test_requires_health_dir_at_config_time(self):
        """Cross-field validation at construction, not first at fit time:
        a multi-host job must not burn bootstrap + compile before
        learning its heartbeat config is incomplete."""
        from dtf_tpu.config import ClusterConfig

        with pytest.raises(ValueError, match="health_dir"):
            ClusterConfig(hb_interval_s=0.5)
        ClusterConfig(hb_interval_s=0.5, health_dir="/shared/hb")
        ClusterConfig(hb_interval_s=0.5,
                      health_dir="tcp://coordinator:8099")
