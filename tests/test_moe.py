"""MoE layer: routing correctness vs a direct per-token reference, capacity
drops, load-balance aux loss, expert-parallel sharded training step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.nn.moe import MoE
from dtf_tpu.parallel import sharding as sh
from dtf_tpu.parallel.mesh import make_mesh


def reference_moe(moe, params, x):
    """Per-token loop: route each token to its top-k experts (no capacity)."""
    b, t, d = x.shape
    xf = np.asarray(x.reshape(-1, d), np.float32)
    logits = xf @ np.asarray(params["router"]["w"])
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    probs = np.asarray(probs)
    out = np.zeros_like(xf)
    for i in range(xf.shape[0]):
        order = np.argsort(-probs[i])[:moe.top_k]
        gates = probs[i][order]
        if moe.top_k > 1:
            gates = gates / gates.sum()
        for gate, e in zip(gates, order):
            h = np.asarray(jax.nn.gelu(jnp.asarray(
                xf[i] @ np.asarray(params["fc1"]["w"][e])
                + np.asarray(params["fc1"]["b"][e]))))
            y = h @ np.asarray(params["fc2"]["w"][e]) \
                + np.asarray(params["fc2"]["b"][e])
            out[i] += gate * y
    return out.reshape(b, t, d)


class TestMoE:
    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_reference_with_ample_capacity(self, top_k):
        moe = MoE(dim=8, mlp_dim=16, num_experts=4, top_k=top_k,
                  capacity_factor=8.0)   # ample: nothing dropped
        params = moe.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2, 6, 8))
        y, aux = moe.apply(params, x)
        assert y.shape == x.shape
        np.testing.assert_allclose(y, reference_moe(moe, params, x),
                                   atol=1e-5)
        assert float(aux) > 0

    def test_capacity_drops_tokens(self):
        moe = MoE(dim=4, mlp_dim=8, num_experts=2, capacity_factor=0.25)
        params = moe.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(2), (1, 16, 4))
        c = moe.capacity(16)
        assert c == 2
        y, _ = moe.apply(params, x)
        # with capacity 2/expert at most 4 tokens processed; the rest must
        # be exactly zero (residual carries them)
        nonzero_tokens = int(jnp.sum(jnp.any(y[0] != 0, axis=-1)))
        assert nonzero_tokens <= 2 * c

    def test_balanced_router_aux_near_one(self):
        """Uniform router -> aux loss ~= 1 (Switch's minimum)."""
        moe = MoE(dim=8, mlp_dim=8, num_experts=4, capacity_factor=8.0)
        params = moe.init(jax.random.key(0))
        params["router"]["w"] = jnp.zeros_like(params["router"]["w"])
        x = jax.random.normal(jax.random.key(3), (4, 32, 8))
        _, aux = moe.apply(params, x)
        np.testing.assert_allclose(float(aux), 1.0, atol=0.05)

    def test_collapsed_router_aux_does_not_saturate(self):
        """f_e must come from PRE-capacity assignments: a router collapsed
        onto one expert gives aux ~= E even when capacity truncates (Switch
        eq. 4); computing f_e post-truncation would report ~1.0 and kill
        the balancing gradient exactly when it is needed."""
        e = 4
        moe = MoE(dim=8, mlp_dim=8, num_experts=e, capacity_factor=1.0)
        params = moe.init(jax.random.key(0))
        w = np.zeros((8, e), np.float32)
        w[:, 0] = 100.0                     # collapse onto expert 0
        params["router"]["w"] = jnp.asarray(w)
        # positive features so the collapsed logit is always the max
        x = jnp.abs(jax.random.normal(jax.random.key(3), (2, 32, 8))) + 0.1
        _, aux = moe.apply(params, x)
        np.testing.assert_allclose(float(aux), float(e), rtol=0.05)

    def test_expert_parallel_train_step(self):
        """Grad step with experts sharded over the 'expert' mesh axis."""
        mesh = make_mesh("data=2,expert=4")
        moe = MoE(dim=8, mlp_dim=16, num_experts=4, capacity_factor=4.0)
        params = moe.init(jax.random.key(0))
        shardings = sh.apply_rules(moe.axes(), mesh)
        params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        assert params["fc1"]["w"].sharding.spec[0] == "expert"
        x = jax.device_put(
            jax.random.normal(jax.random.key(4), (8, 16, 8)),
            sh.batch_spec(mesh, 3))

        @jax.jit
        def loss_fn(params, x):
            y, aux = moe.apply(params, x)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.grad(loss_fn)(params, x)
        for leaf in jax.tree_util.tree_leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_moe_bert_trains_expert_parallel(self):
        """MoE-BERT: full DP x EP train step; aux loss wired into MLM loss."""
        from dtf_tpu import optim
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        mesh = make_mesh("data=2,expert=4")
        cfg = BertConfig.tiny(moe_experts=4)
        model = BertMLM(cfg)
        shardings = sh.apply_rules(model.axes(), mesh)
        opt = optim.adam(1e-3)
        state = init_state(model, opt, seed=0, mesh=mesh,
                           param_shardings=shardings)
        # stacked layers: leading L dim, then expert dim sharded
        assert state["params"]["layers"]["moe"]["fc1"]["w"].sharding.spec[1] \
            == "expert"
        step = make_train_step(model.loss, opt, mesh, donate=False)
        toks = np.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, cfg.max_len)), np.int32)
        state, metrics = step(state, put_global_batch(mesh, toks),
                              jax.random.key(0))
        assert np.isfinite(float(metrics["loss"]))
        assert float(metrics["moe_aux"]) > 0

    def test_gradients_flow_to_router(self):
        moe = MoE(dim=4, mlp_dim=8, num_experts=2, capacity_factor=4.0)
        params = moe.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(5), (1, 8, 4))

        def loss_fn(params):
            y, aux = moe.apply(params, x)
            return jnp.mean(y ** 2) + 0.01 * aux

        g = jax.grad(loss_fn)(params)
        assert float(jnp.sum(jnp.abs(g["router"]["w"]))) > 0


class TestActiveParamCount:
    def test_moe_counts_only_routed_experts(self):
        """MFU accounting (workloads/_driver.py): top-1 of E experts means
        only 1/E of the expert FFN weights are active per token; the
        router and all dense weights count fully."""
        from dtf_tpu.models.bert import BertConfig, BertMLM

        cfg = BertConfig.tiny(moe_experts=4, moe_top_k=1)
        model = BertMLM(cfg)
        params = model.init(jax.random.key(0))
        total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
        expert = sum(
            int(leaf.size)
            for name, sub in params["layers"]["moe"].items()
            if name != "router"
            for leaf in jax.tree_util.tree_leaves(sub))
        active = model.active_param_count(params)
        assert active == total - int(expert * 0.75)
        assert active < total

    def test_dense_equals_total(self):
        from dtf_tpu.models.bert import BertConfig, BertMLM

        cfg = BertConfig.tiny()
        model = BertMLM(cfg)
        params = model.init(jax.random.key(0))
        total = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
        assert model.active_param_count(params) == total
