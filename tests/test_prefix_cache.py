"""Prefix/prompt KV caching (DESIGN.md §7.7, ISSUE 20): the
sharing-aware paged pool and everything stacked on it.

The ISSUE-level pins live here:

* **warm == cold, bitwise** — a prompt served from matched prefix
  blocks (suffix-only prefill) must emit tokens IDENTICAL to the same
  prompt cold-prefilled, greedy AND sampled, solo and coalesced;
* **sharing is leak-free under churn** — waves of shared-prefix traffic
  with seeded random cancels return every non-trash block to the
  free/cached tiers, and the §7.5 hot-prefix narrowing counts a shared
  block once (parked blocks stay inside the resident prefix);
* **poison on a SHARED block evicts every sharer** — no surviving
  stream ever emits a NaN-derived token, queued pin-holders lose their
  discount and cold-prefill, and the scrubbed blocks recycle cleanly;
* **the hit-rate gate is falsifiable** — `min_prefix_hit_rate` through
  the one `check_gates` path FAILS on a summary that lacks the key
  (absent = the run served cold = config regression) and at an absurd
  threshold on a real summary.
"""

import numpy as np
import pytest

from dtf_tpu.serve import ServingEngine, VirtualClock
from dtf_tpu.serve.paged_kv import BlockAllocator, chunk_digests

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    """One model object for the whole module (compiled-step cache is
    keyed on the model instance — same idiom as test_serve.py)."""
    import jax

    from dtf_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig.tiny())
    return model, model.init(jax.random.key(0))


def _mk_engine(model, params, **kw):
    kw.setdefault("clock", VirtualClock())
    kw.setdefault("num_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("blocks_per_slot", 8)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(model, params, **kw)


def _shared_trace(n, *, prefix, seed=0, start_rid=0, qps=200.0,
                  sampled_temperature=0.8, o_lens=(4, 6, 8)):
    """Shared-prefix arrivals: every prompt = ``prefix`` + a seeded
    random suffix; even rids greedy, odd rids sampled (the parity pin
    must cover both decode paths)."""
    rng = np.random.default_rng(seed)
    trace, t = [], 0.0
    for i in range(n):
        rid = start_rid + i
        t += float(rng.exponential(1.0)) / qps
        sfx = rng.integers(0, 128, (int(rng.integers(1, 6)),))
        trace.append((t, {
            "rid": rid,
            "prompt": np.concatenate([prefix, sfx]).astype(np.int32),
            "max_new_tokens": int(rng.choice(o_lens)),
            "temperature": 0.0 if rid % 2 == 0 else sampled_temperature,
        }))
    return trace


# ---------------------------------------------------------------------------
# sharing-aware allocator (pure Python, no jax)
# ---------------------------------------------------------------------------


class TestSharingAllocator:
    def _digests(self, tokens, bs=4):
        return chunk_digests(tokens, bs, len(tokens) // bs)

    def test_refcount_zero_parks_then_lru_reclaims(self):
        """A registered block parks in the cached tier on release (still
        matchable), and allocation pressure drains the FREE list first,
        then the cached tier oldest-parked first — de-indexing on
        reclaim."""
        a = BlockAllocator(6)                      # usable ids 1..5
        d = self._digests(list(range(12)))         # 3-link chain
        b = a.allocate(3)
        assert a.register_chain(d, b) == 3
        a.free(b)
        assert a.cached_blocks == 3 and a.used_blocks == 0
        assert a.free_blocks == 5                  # parked counts as free
        assert a.match_chain(d) == b               # still matchable
        # pressure: 2 true-free blocks first, then the OLDEST parked
        got = a.allocate(3)
        assert got == [4, 5, b[0]]
        assert a.cached_blocks == 2
        assert a.match_chain(d) == []              # chain head de-indexed

    def test_acquire_pins_live_and_unparks_cached(self):
        a = BlockAllocator(6)
        d = self._digests(list(range(8)))
        b = a.allocate(2)
        a.register_chain(d, b)
        a.acquire(b)                               # second owner
        assert a.ref_count(b[0]) == 2
        a.free(b)                                  # first owner leaves
        assert a.ref_count(b[0]) == 1 and a.cached_blocks == 0
        a.free(b)                                  # last owner: parks
        assert a.ref_count(b[0]) == 0 and a.cached_blocks == 2
        a.acquire(b)                               # un-park
        assert a.ref_count(b[0]) == 1 and a.cached_blocks == 0
        a.free(b)
        with pytest.raises(ValueError, match="neither live nor cached"):
            a.acquire([5])                         # free-list block = bug

    def test_match_chain_stops_at_first_miss(self):
        """The radix property: digests chain over the whole prefix, so
        a diverging FIRST chunk unmatches every later one even when the
        later chunks' raw tokens are identical."""
        a = BlockAllocator(8)
        toks = list(range(12))
        b = a.allocate(3)
        a.register_chain(self._digests(toks), b)
        assert a.match_chain(self._digests(toks)) == b
        assert a.match_chain(self._digests(toks[:8])) == b[:2]
        diverged = [99] + toks[1:]                 # same chunks 2..3
        assert a.match_chain(self._digests(diverged)) == []
        # a hole mid-chain ends the walk even if a descendant is indexed
        assert a.match_chain([b"nope", self._digests(toks)[1]]) == []

    def test_register_first_writer_wins_and_live_guard(self):
        a = BlockAllocator(8)
        d = self._digests(list(range(8)))
        b1 = a.allocate(2)
        assert a.register_chain(d, b1) == 2
        b2 = a.allocate(2)                         # racing copy
        assert a.register_chain(d, b2) == 0        # keeps b1
        assert a.match_chain(d) == b1
        a.free(b2)
        assert a.cached_blocks == 0                # unregistered: truly freed
        with pytest.raises(ValueError, match="not live"):
            a.register_chain(self._digests(list(range(50, 54))), [b2[0]])

    def test_invalidate_blocks_poison_path(self):
        """De-index poisoned content: a parked victim falls to the free
        list (content was all that parked it), a LIVE victim stays owned
        and frees normally — to the free list, not back into the cached
        tier."""
        a = BlockAllocator(8)
        d = self._digests(list(range(12)))
        b = a.allocate(3)
        a.register_chain(d, b)
        a.free([b[2]])                             # park just the tail
        assert a.cached_blocks == 1
        a.invalidate_blocks(b)
        assert a.cached_blocks == 0
        assert a.match_chain(d) == []
        assert a.ref_count(b[0]) == 1              # live head still owned
        before = a.free_blocks
        a.free(b[:2])
        assert a.cached_blocks == 0                # no re-park after poison
        assert a.free_blocks == before + 2

    def test_highest_used_spans_cached_tier(self):
        """Satellite pin (hot-prefix narrowing composition): parked
        blocks are live content a future match maps straight into a
        table, so the narrowed decode's resident-prefix bound must keep
        covering them — and a SHARED block counts once, not once per
        owner."""
        a = BlockAllocator(8)
        d = self._digests(list(range(12)))
        b = a.allocate(3)                          # ids 1..3
        a.register_chain(d, b)
        a.acquire(b)                               # 2 owners, same blocks
        assert a.highest_used() == 3               # counted once
        a.free(b)
        a.free(b)                                  # all owners gone: parked
        assert a.used_blocks == 0
        assert a.highest_used() == 3               # parked still resident
        a.invalidate_blocks(b)
        assert a.highest_used() == 0

    def test_cache_off_degenerates_to_plain_free_list(self):
        """An allocator that never registers content behaves bit-for-bit
        like the pre-cache free list (the cache-off determinism pin at
        the unit level)."""
        a = BlockAllocator(8)
        assert a.allocate(3) == [1, 2, 3]
        a.free([2])
        assert a.cached_blocks == 0
        assert a.allocate(2) == [2, 4]
        assert a.free_blocks == a.num_blocks - 1 - a.used_blocks


# ---------------------------------------------------------------------------
# engine: warm-vs-cold parity, churn, shared-block poison (jax)
# ---------------------------------------------------------------------------


class TestPrefixEngine:
    def test_warm_tokens_bitwise_cold_coalesced_and_solo(self, tiny_model):
        """THE tentpole pin: the same shared-prefix trace through a
        cache-on engine (suffix-only prefill over matched blocks) and a
        cache-off engine (cold prefill) emits bitwise-identical streams
        — greedy and sampled rids, batched and solo prefill — and the
        warm arm actually hit (hits > 0, not a vacuous pass)."""
        model, params = tiny_model
        rng = np.random.default_rng(3)
        prefix = rng.integers(0, 128, (8,))        # 2 full blocks @ bs=4
        trace = _shared_trace(10, prefix=prefix, seed=3)
        cold = _mk_engine(model, params, prefix_cache=False).run(trace)
        for coalesce in (True, False):
            eng = _mk_engine(model, params, coalesce_prefill=coalesce)
            warm = eng.run(trace)
            for rid, ref in cold.items():
                assert warm[rid].status == ref.status == "completed"
                assert warm[rid].tokens == ref.tokens, (
                    f"rid {rid} (coalesce={coalesce}, "
                    f"{'greedy' if rid % 2 == 0 else 'sampled'}) diverged")
            s = eng.summary()
            assert s["prefix_hit_blocks"] > 0
            assert s["prefix_hit_rate"] > 0
            assert s["prefix_lookups"] == len(trace)

    def test_churn_with_cancels_leak_free_and_narrow_composes(
            self, tiny_model):
        """Satellite pin: waves of shared-prefix traffic with seeded
        random mid-flight cancels leave zero leaked blocks (parked
        cached blocks are reclaimable, not leaked), repeat visitors
        still hit, and the §7.5 narrowed decode's resident prefix keeps
        covering the parked tier (no migration under a live share)."""
        from dtf_tpu.bench.serve_load import _churn_with_cancels
        model, params = tiny_model
        rng = np.random.default_rng(9)
        prefix = rng.integers(0, 128, (8,))
        eng = _mk_engine(model, params)
        alloc = eng.scheduler.allocator
        cancelled = 0
        for wave in range(3):
            trace = _shared_trace(8, prefix=prefix, seed=5,
                                  start_rid=wave * 8)
            cancelled += _churn_with_cancels(eng, trace, seed=100 + wave)
            # hot-prefix narrowing composes: the resident prefix covers
            # every used AND parked block, counted by physical id
            assert eng.pool.hot_blocks >= alloc.highest_used() + 1
        assert cancelled > 0, "churn never cancelled anything"
        # leak audit: every non-trash block is free or parked
        assert alloc.num_blocks - 1 - alloc.free_blocks == 0
        assert alloc.cached_blocks > 0            # the tier was exercised
        assert eng.summary()["prefix_hit_blocks"] > 0

    def test_poison_on_shared_block_evicts_every_active_sharer(
            self, tiny_model):
        """Satellite pin: kv_poison landing on blocks shared by several
        ACTIVE streams evicts them ALL (each slot's own finite-logits
        flag trips in the same iteration) — no survivor emits a
        NaN-derived token — and a follow-up wave with the same prompts
        cold-prefills cleanly to the reference streams (scrubbed,
        de-indexed, recycled)."""
        import jax.numpy as jnp

        from dtf_tpu.resilience.chaos import FaultPlan
        model, params = tiny_model
        rng = np.random.default_rng(21)
        prefix = rng.integers(0, 128, (8,))
        prompts = [np.concatenate([prefix,
                                   rng.integers(0, 128, (1 + i,))])
                   .astype(np.int32) for i in range(3)]
        refs = [np.asarray(model.generate(
            params, jnp.asarray(p)[None], 8,
            temperature=0.0))[0, len(p):].tolist() for p in prompts]
        # rid 0 cold-prefills and registers; 1..2 arrive after its
        # prefill, match the shared chain, and decode alongside it
        trace = [(0.0 if i == 0 else 0.01,
                  dict(rid=i, prompt=p, max_new_tokens=8))
                 for i, p in enumerate(prompts)]
        plan = FaultPlan.parse("kv_poison@6", process_index=0)
        eng = _mk_engine(model, params, chaos=plan)
        res = eng.run(trace)
        assert [res[i].status for i in range(3)] == ["failed"] * 3, \
            {i: res[i].status for i in range(3)}
        for i in range(3):
            # nothing NaN-derived ever reached the stream: every token
            # emitted BEFORE the poison matches the clean reference
            got = res[i].tokens or []
            assert got == refs[i][:len(got)], f"sharer {i} emitted garbage"
        # recovery wave: same prompts, cold prefill, clean completions
        res2 = eng.run([(0.0, dict(rid=10 + i, prompt=p,
                                   max_new_tokens=8))
                        for i, p in enumerate(prompts)])
        for i in range(3):
            assert res2[10 + i].status == "completed"
            assert res2[10 + i].tokens == refs[i], f"recycled NaN hit {i}"
        alloc = eng.scheduler.allocator
        assert alloc.num_blocks - 1 - alloc.free_blocks == 0

    def test_poison_strips_queued_pins_then_cold_prefills(self, tiny_model):
        """Satellite pin, queued half: a QUEUED request holding submit-
        time pins on the poisoned chain just loses its admission
        discount — it cold-prefills when admitted and completes with
        the reference stream (its tokens were never derived from the
        bad rows)."""
        import jax.numpy as jnp

        from dtf_tpu.resilience.chaos import FaultPlan
        model, params = tiny_model
        rng = np.random.default_rng(33)
        prefix = rng.integers(0, 128, (8,))
        prompts = [np.concatenate([prefix,
                                   rng.integers(0, 128, (2,))])
                   .astype(np.int32) for _ in range(3)]
        refs = [np.asarray(model.generate(
            params, jnp.asarray(p)[None], 8,
            temperature=0.0))[0, len(p):].tolist() for p in prompts]
        plan = FaultPlan.parse("kv_poison@6", process_index=0)
        # 2 slots: rid 2 queues behind the two active sharers, pinned
        eng = _mk_engine(model, params, num_slots=2, chaos=plan)
        res = eng.run([(0.0 if i == 0 else 0.01,
                        dict(rid=i, prompt=p, max_new_tokens=8))
                       for i, p in enumerate(prompts)])
        assert res[0].status == "failed"
        assert res[1].status == "failed"
        assert res[2].status == "completed"
        assert res[2].tokens == refs[2], "queued pin-holder got bad rows"
        alloc = eng.scheduler.allocator
        assert alloc.num_blocks - 1 - alloc.free_blocks == 0


# ---------------------------------------------------------------------------
# gate plumbing: Gate -> thresholds -> check_gates (jax-free)
# ---------------------------------------------------------------------------


class TestPrefixHitRateGate:
    def _report(self, **serving):
        return {"telemetry": {"serving": serving}}

    def test_gate_threshold_plumbing(self):
        from dtf_tpu.scenarios.spec import Gate
        g = Gate(max_final_cost=None, min_goodput=0.01,
                 min_goodput_qps=1.0, min_prefix_hit_rate=0.8)
        assert g.thresholds()["min_prefix_hit_rate"] == 0.8
        g0 = Gate(max_final_cost=None, min_goodput=0.01,
                  min_goodput_qps=1.0)
        assert "min_prefix_hit_rate" not in g0.thresholds()

    def test_check_gates_pass_fail_and_absence_fails(self):
        """Falsifiability: absent key = the run served cold = FAIL (the
        same rule as max_control_rollbacks — a cell whose engine lost
        its prefix_cache flag must not pass vacuously), and an absurd
        threshold fails on a REAL summary (the gate measures)."""
        from dtf_tpu.telemetry.report import check_gates
        warm = self._report(prefix_hit_rate=0.9375, goodput_qps=5.0)
        ok, lines = check_gates(warm, min_prefix_hit_rate=0.8)
        assert ok and any("min_prefix_hit_rate: OK" in ln for ln in lines)
        assert not check_gates(warm, min_prefix_hit_rate=0.999)[0]
        cold = self._report(goodput_qps=5.0)       # no prefix keys at all
        ok, lines = check_gates(cold, min_prefix_hit_rate=0.8)
        assert not ok
        assert any("min_prefix_hit_rate" in ln and "FAIL" in ln
                   for ln in lines)
        # unarmed: a cold summary is fine (the gate is opt-in per cell)
        assert check_gates(cold)[0]

    def test_default_matrix_carries_the_cell(self):
        from dtf_tpu.scenarios.spec import default_matrix
        cells = {s.name: s for s in default_matrix()}
        cell = cells["serve_prefix_cache"]
        assert dict(cell.extra)["prefix_cache"] == 1
        assert cell.gate.min_prefix_hit_rate >= 0.8
        assert cell.gate.min_goodput_qps > 0      # serve-cell contract
        # no other cell arms the gate by accident (absence must FAIL,
        # so arming it on a cache-off cell would break that cell)
        for name, s in cells.items():
            if name != "serve_prefix_cache":
                assert s.gate.min_prefix_hit_rate == 0, name


# ---------------------------------------------------------------------------
# fleet prefix-affinity routing (jax-free)
# ---------------------------------------------------------------------------


class TestFleetAffinity:
    def _acc(self, n=2, **cfg_kw):
        from dtf_tpu.serve.fleet import FleetAcceptor, FleetConfig, Replica
        reps = [Replica(i, ("127.0.0.1", 0)) for i in range(n)]
        return FleetAcceptor(reps, config=FleetConfig(**cfg_kw)), reps

    def test_hint_table_is_bounded_lru(self):
        from dtf_tpu.serve.fleet import Replica
        r = Replica(0, ("127.0.0.1", 0))
        sigs = [chunk_digests(list(range(i, i + 16)), 16, 1)
                for i in range(6)]
        for s in sigs:
            r.note_prefix(s, cap=4)
        assert len(r.prefix_hints) == 4            # oldest two evicted
        assert r.match_prefix(sigs[0]) == 0
        assert r.match_prefix(sigs[5]) == 1
        # re-noting renews LRU position
        r.note_prefix(sigs[2], cap=4)
        r.note_prefix(chunk_digests(list(range(100, 116)), 16, 1), cap=4)
        assert r.match_prefix(sigs[2]) == 1        # renewed, survived

    def test_affinity_prefers_warm_replica_but_never_overrides_health(self):
        """The routing bonus is a TIEBREAKER: equal-health replicas
        route to the one whose recent admissions share the prompt's
        leading chunks, but a browned-out warm replica still loses to a
        healthy cold one (max 4 x affinity_weight vs the 25/15/10
        health terms)."""
        acc, (r0, r1) = self._acc()
        prompt = list(range(64))                   # 4 x 16-token chunks
        sig = acc._prefix_sig({"prompt": prompt})
        assert len(sig) == 4
        r1.note_prefix(sig, cap=64)
        assert acc._score(r1, sig) < acc._score(r0, sig)
        assert acc._route(prefix_sig=sig) is r1
        # health dominates: brownout on the warm replica flips the route
        r1.stats = {"brownout_level": 1}
        assert acc._route(prefix_sig=sig) is r0
        # and with no signature the bonus never applies
        assert acc._score(r0) == acc._score(r1) - 25.0

    def test_sig_guards_and_partial_match(self):
        acc, (r0, _) = self._acc()
        assert acc._prefix_sig({"prompt": None}) == []
        assert acc._prefix_sig({"prompt": "not tokens"}) == []
        assert acc._prefix_sig({"prompt": list(range(8))}) == []  # < 1 chunk
        long_sig = acc._prefix_sig({"prompt": list(range(64))})
        r0.note_prefix(long_sig[:2], cap=64)
        assert r0.match_prefix(long_sig) == 2      # longest shared prefix
