"""Matmul benchmark tests on the simulated 8-device mesh (SURVEY.md §4)."""

import time

import jax
import numpy as np
import pytest

from dtf_tpu.bench.matmul import (
    MatmulBenchConfig, make_operands, run_matmul_bench, verify_correctness,
    peak_flops_per_chip, _operand_shardings,
)
from dtf_tpu.parallel.mesh import make_mesh


class TestMatmulBench:
    def test_correctness_sharded_1d(self, mesh8):
        err = verify_correctness(mesh8, n=128)
        assert err < 1e-3

    def test_correctness_sharded_2d(self, mesh_2d):
        """The '2-worker PS matmul -> ICI mesh' config (BASELINE.md row 2),
        generalized: A rows on data, B cols on tensor."""
        err = verify_correctness(mesh_2d, n=128)
        assert err < 1e-3

    def test_operand_shardings(self, mesh_2d):
        a_sh, b_sh = _operand_shardings(mesh_2d)
        from jax.sharding import PartitionSpec as P
        assert a_sh.spec == P(("data",), None)
        assert b_sh.spec == P(None, "tensor")

    def test_bench_runs_and_reports(self, mesh8):
        cfg = MatmulBenchConfig(n=64, mesh=mesh8, dtype="float32",
                                target_long_s=0.05, reps=1)
        r = run_matmul_bench(cfg)
        assert r["n_chips"] == 8
        assert r["matmul_time_us"] > 0
        assert r["tflops_per_chip"] > 0
        # CPU has no roofline entry.
        assert r["peak_tflops_per_chip"] is None

    def test_operands_deterministic(self, mesh8):
        a1, b1 = make_operands(mesh8, 64, "float32", seed=1)
        a2, b2 = make_operands(mesh8, 64, "float32", seed=1)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))

    def test_peak_table_unknown_device_none(self):
        assert peak_flops_per_chip(jax.devices()[0]) is None  # CPU


class TestOutageAwareEntry:
    """bench.py prints ONE structured JSON line even when the TPU relay is
    dead (observed round 3: backend init either raises Unavailable or hangs
    forever), so BENCH_r*.json distinguishes outage from harness bugs."""

    def _run_main(self, capsys, **kw):
        import bench

        rc = bench.main(**kw)
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1, "exactly one JSON line, success or failure"
        return rc, __import__("json").loads(out[0])

    def test_init_raise_is_tpu_unavailable(self, capsys):
        def dead_init(timeout_s):
            raise RuntimeError("UNAVAILABLE: failed to connect to backend")

        rc, line = self._run_main(capsys, _init=dead_init)
        assert rc == 1
        assert line["error"] == "tpu_unavailable"
        assert line["metric"] == "matmul_tflops_per_chip"
        assert line["value"] is None and line["vs_baseline"] is None
        assert line["detail"]["stage"] == "backend_init"
        assert "UNAVAILABLE" in line["detail"]["reason"]

    def test_watchdog_timeout_is_tpu_unavailable(self, capsys):
        """The watchdog's TimeoutError (hung-relay mode) formats the same
        outage line as a raised init error."""
        def timed_out_init(timeout_s):
            raise TimeoutError("jax backend init did not complete within 0s")

        rc, line = self._run_main(capsys, _init=timed_out_init)
        assert rc == 1
        assert line["error"] == "tpu_unavailable"
        assert "did not complete" in line["detail"]["reason"]

    def test_broken_jax_import_is_harness_error(self, capsys):
        """A venv where jax can't import is a harness bug, not an outage."""
        def broken_init(timeout_s):
            raise ImportError("No module named 'jax'")

        rc, line = self._run_main(capsys, _init=broken_init)
        assert rc == 1
        assert line["error"] == "harness_error"

    def test_bad_ns_env_is_config_error(self, capsys, monkeypatch):
        monkeypatch.setenv("DTF_BENCH_NS", "4096;8192")
        rc, line = self._run_main(capsys, _init=lambda t: ["cpu:0"])
        assert rc == 1
        assert line["error"] == "config_error"
        assert line["detail"]["stage"] == "config"

    def test_bad_timeout_env_is_config_error(self, capsys, monkeypatch):
        monkeypatch.setenv("DTF_BENCH_INIT_TIMEOUT_S", "10m")
        rc, line = self._run_main(capsys, _init=lambda t: ["cpu:0"])
        assert rc == 1
        assert line["error"] == "config_error"

    def test_broken_dtf_import_is_harness_error(self, capsys, monkeypatch):
        """The import STATEMENT failing (broken package) is a harness bug."""
        import sys

        monkeypatch.setitem(sys.modules, "dtf_tpu.bench.matmul", None)
        rc, line = self._run_main(capsys, _init=lambda t: ["cpu:0"])
        assert rc == 1
        assert line["error"] == "harness_error"
        assert line["detail"]["stage"] == "sweep"

    def test_lazy_import_error_mid_run_is_benchmark_error(
            self, capsys, monkeypatch):
        """An ImportError raised while sweep is RUNNING means the run died,
        not that the harness is broken."""
        import dtf_tpu.bench.matmul as matmul

        def lazy_import_dies(*a, **k):
            raise ModuleNotFoundError("no backend plugin module")

        monkeypatch.setattr(matmul, "sweep", lazy_import_dies)
        rc, line = self._run_main(capsys, _init=lambda t: ["cpu:0"])
        assert rc == 1
        assert line["error"] == "benchmark_error"

    @pytest.mark.parametrize("var,val", [
        ("DTF_BENCH_DEADLINE_S", "0"),
        ("DTF_BENCH_INIT_TIMEOUT_S", "inf"),
        ("DTF_BENCH_DEADLINE_S", "nan"),
        ("DTF_BENCH_NS", "0"),
        ("DTF_BENCH_NS", "-4096"),
    ])
    def test_out_of_range_env_is_config_error(self, capsys, monkeypatch,
                                              var, val):
        monkeypatch.setenv(var, val)
        rc, line = self._run_main(capsys, _init=lambda t: ["cpu:0"])
        assert rc == 1
        assert line["error"] == "config_error"

    def test_watchdog_times_out_hung_probe(self, monkeypatch):
        """init_backend itself enforces the timeout on a wedged probe thread
        (patched via the bench._Thread seam so unrelated threads are
        untouched)."""
        import bench
        import threading

        hang = threading.Event()

        class HungProbe(threading.Thread):
            def run(self):
                hang.wait(5)  # longer than the watchdog below

        monkeypatch.setattr(bench, "_Thread", HungProbe)
        with pytest.raises(TimeoutError, match="did not complete"):
            bench.init_backend(timeout_s=0.1)
        hang.set()

    def test_mid_sweep_failure_is_benchmark_error(self, capsys, monkeypatch):
        import dtf_tpu.bench.matmul as matmul

        def dying_sweep(*a, **k):
            raise RuntimeError("relay dropped mid-sweep")

        monkeypatch.setattr(matmul, "sweep", dying_sweep)
        rc, line = self._run_main(capsys, _init=lambda t: ["cpu:0"])
        assert rc == 1
        assert line["error"] == "benchmark_error"
        assert line["detail"]["stage"] == "sweep"

    def test_real_init_succeeds_on_cpu(self, monkeypatch):
        """Pin the platform: on a TPU-plugin image with a hung relay this
        would otherwise block the fast suite for the full watchdog."""
        import bench

        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        devices = bench.init_backend(timeout_s=120)
        assert len(devices) >= 1

    def test_bad_jax_platforms_is_config_error(self, capsys, monkeypatch):
        """A JAX_PLATFORMS typo (jax raises 'unknown backend') classifies
        as config_error, not a relay outage; platform names are an open
        PJRT registry so there is no allowlist to validate against."""
        monkeypatch.setenv("JAX_PLATFORMS", "tup")

        def unknown_backend_init(timeout_s):
            raise RuntimeError("Unknown backend: 'tup' requested, but no "
                               "platforms are present.")

        rc, line = self._run_main(capsys, _init=unknown_backend_init)
        assert rc == 1
        assert line["error"] == "config_error"
        assert "JAX_PLATFORMS" in line["detail"]["reason"]

    def test_valid_platform_unregistered_is_outage(self, capsys,
                                                   monkeypatch):
        """JAX_PLATFORMS=tpu (a core name) + 'unknown backend' means the
        plugin failed to register — an outage, not a config typo."""
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")

        def unregistered_init(timeout_s):
            raise RuntimeError("Unknown backend: 'tpu' requested, but no "
                               "platforms that are instances of tpu are "
                               "present.")

        # _preflight=None: this test targets the raise-mode classifier;
        # the real subprocess probe would just burn a jax import here.
        rc, line = self._run_main(capsys, _init=unregistered_init,
                                  _preflight=None)
        assert rc == 1
        assert line["error"] == "tpu_unavailable"

    def test_preflight_hang_fails_fast_as_tpu_unavailable(self, capsys,
                                                          monkeypatch):
        """A hung preflight probe must fail the run BEFORE init_backend
        ever runs — the fast path that replaces burning the full 600s
        outer timeout on a dead relay.  Zero-width retry windows keep
        the test instant; the probe count lands in the reason."""
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        monkeypatch.setenv("DTF_BENCH_PREFLIGHT_RETRY_WAIT_S", "0")

        def never_init(timeout_s):
            raise AssertionError("init_backend must not run after a hung "
                                 "preflight")

        rc, line = self._run_main(
            capsys, _init=never_init,
            _preflight=lambda t: (True, f"probe hung past {t:.0f}s"))
        assert rc == 1
        assert line["error"] == "tpu_unavailable"
        assert line["detail"]["stage"] == "preflight"
        assert "hung" in line["detail"]["reason"]
        assert "3 probe(s)" in line["detail"]["reason"]  # 1 + 2 retries

    def test_preflight_retry_next_window_recovers(self, capsys,
                                                  monkeypatch):
        """The r03-r05 stall fix: a relay that hangs for the first probe
        window but is back for a retry must let the run PROCEED to the
        real init instead of recording another tpu_unavailable round."""
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        monkeypatch.setenv("DTF_BENCH_PREFLIGHT_RETRIES", "3")
        monkeypatch.setenv("DTF_BENCH_PREFLIGHT_RETRY_WAIT_S", "0")
        calls = []

        def flaky_probe(t):
            calls.append(t)
            return (len(calls) < 3, "hung" if len(calls) < 3 else "")

        def init_ok(timeout_s):
            # Raising here (after the probe recovered) proves control
            # reached the real init; the classifier turns it into a
            # backend_init line, which is the assertion below.
            raise RuntimeError("UNAVAILABLE: but we did try init")

        rc, line = self._run_main(capsys, _init=init_ok,
                                  _preflight=flaky_probe)
        assert len(calls) == 3          # hang, hang, recovered
        assert line["detail"]["stage"] == "backend_init"

    def test_preflight_retries_env_validation(self, capsys, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        monkeypatch.setenv("DTF_BENCH_PREFLIGHT_RETRIES", "-1")
        rc, line = self._run_main(
            capsys, _init=lambda t: [],
            _preflight=lambda t: (False, ""))
        assert rc == 1
        assert line["error"] == "config_error"
        assert "DTF_BENCH_PREFLIGHT_RETRIES" in line["detail"]["reason"]

    def test_preflight_retries_disabled_single_probe(self, capsys,
                                                     monkeypatch):
        """DTF_BENCH_PREFLIGHT_RETRIES=0 restores the one-shot behavior
        (operators who prefer failing at the first hang)."""
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        monkeypatch.setenv("DTF_BENCH_PREFLIGHT_RETRIES", "0")
        calls = []

        def hang_probe(t):
            calls.append(t)
            return True, "hung"

        rc, line = self._run_main(capsys, _init=lambda t: [],
                                  _preflight=hang_probe)
        assert rc == 1 and len(calls) == 1
        assert "1 probe(s)" in line["detail"]["reason"]

    def test_preflight_skipped_on_cpu_only_run(self, capsys, monkeypatch):
        """JAX_PLATFORMS=cpu cannot hit the relay's hang mode: the probe
        must not run (no subprocess tax), and raise-mode errors keep
        their existing backend_init classification."""
        monkeypatch.setenv("JAX_PLATFORMS", "cpu")

        def must_not_probe(t):
            raise AssertionError("preflight must be skipped on cpu")

        def dead_init(timeout_s):
            raise RuntimeError("UNAVAILABLE: failed to connect")

        rc, line = self._run_main(capsys, _init=dead_init,
                                  _preflight=must_not_probe)
        assert rc == 1
        assert line["detail"]["stage"] == "backend_init"

    def test_preflight_raise_mode_falls_through_to_classifier(
            self, capsys, monkeypatch):
        """A probe that exits with an ERROR (not a hang) is not preflight's
        verdict: the real init re-raises it under the existing outage/
        config classifiers."""
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")

        def dead_init(timeout_s):
            raise RuntimeError("UNAVAILABLE: relay refused")

        rc, line = self._run_main(
            capsys, _init=dead_init,
            _preflight=lambda t: (False, ""))   # probe raised quickly
        assert rc == 1
        assert line["error"] == "tpu_unavailable"
        assert line["detail"]["stage"] == "backend_init"

    def test_preflight_disabled_by_env(self, capsys, monkeypatch):
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        monkeypatch.setenv("DTF_BENCH_PREFLIGHT_TIMEOUT_S", "0")

        def must_not_probe(t):
            raise AssertionError("preflight disabled by env")

        def dead_init(timeout_s):
            raise RuntimeError("UNAVAILABLE")

        rc, line = self._run_main(capsys, _init=dead_init,
                                  _preflight=must_not_probe)
        assert line["detail"]["stage"] == "backend_init"

    def test_bad_preflight_env_is_config_error(self, capsys, monkeypatch):
        monkeypatch.setenv("DTF_BENCH_PREFLIGHT_TIMEOUT_S", "-3")
        rc, line = self._run_main(capsys, _init=lambda t: ["cpu:0"])
        assert rc == 1
        assert line["error"] == "config_error"
        assert "PREFLIGHT" in line["detail"]["reason"]

    def test_preflight_probe_kills_hung_subprocess(self, monkeypatch):
        """The real probe against a wedged child: verdict within the short
        timeout, child killed, no zombie."""
        import bench

        monkeypatch.setattr(bench, "_PREFLIGHT_SRC",
                            "import time\ntime.sleep(60)\n")
        t0 = time.perf_counter()
        hung, why = bench.preflight_probe(1.0)
        assert hung is True
        assert "hung" in why
        assert time.perf_counter() - t0 < 30    # killed, not waited out

    def test_preflight_probe_ok_on_healthy_backend(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "_PREFLIGHT_SRC", "pass\n")
        hung, why = bench.preflight_probe(60)
        assert hung is False

    def test_deadline_abort_fires_in_subprocess(self):
        """The whole-run deadline (the os._exit path no in-process test can
        reach) kills a hung run with ONE deadline JSON line.  Whether the
        1s deadline beats backend init (tpu_unavailable) or strikes during
        the sweep (benchmark_error) depends on import-cache warmth; the
        pinned contract is stage=deadline, rc=1, one line."""
        import json
        import os
        import pathlib
        import subprocess
        import sys

        root = pathlib.Path(__file__).resolve().parent.parent
        env = os.environ.copy()
        # Deadline far below any possible jax-import+sweep time, and an N
        # that cannot finish in it on CPU either way — the Timer must win.
        env.update({"JAX_PLATFORMS": "cpu", "DTF_BENCH_NS": "4096",
                    "DTF_BENCH_DEADLINE_S": "0.05",
                    "DTF_BENCH_INIT_TIMEOUT_S": "120"})
        p = subprocess.run([sys.executable, str(root / "bench.py")],
                           capture_output=True, text=True, timeout=300,
                           cwd=root, env=env)
        assert p.returncode == 1
        lines = [l for l in p.stdout.strip().splitlines()
                 if l.startswith("{")]
        assert len(lines) == 1, p.stdout + p.stderr
        line = json.loads(lines[0])
        assert line["error"] in ("tpu_unavailable", "benchmark_error")
        assert line["detail"]["stage"] == "deadline"


class TestInt8Quality:
    @pytest.mark.slow
    def test_trained_checkpoint_path(self, tmp_path):
        """--ckpt scores TRAINED weights: train tiny for a few steps via
        the lm workload, checkpoint, and confirm the harness (a) restores
        the trained params (loss on the training distribution beats fresh
        init), (b) reports scale-dispersion stats."""
        import jax
        import jax.numpy as jnp

        from dtf_tpu.bench.int8_quality import (load_checkpoint_params,
                                                run, scale_stats)
        from dtf_tpu.data.datasets import synthetic_text
        from dtf_tpu.models.gpt import GPT, GPTConfig
        from dtf_tpu.workloads import lm

        rc = lm.main(["--preset", "tiny", "--steps", "8",
                      "--checkpoint_every", "6", "--batch_size", "8",
                      "--logdir", str(tmp_path)])
        assert rc == 0
        params, step = load_checkpoint_params(str(tmp_path / "checkpoints"))
        assert step is not None and step >= 6
        cfg = GPTConfig.tiny()
        m = GPT(cfg)
        toks = jnp.asarray(synthetic_text(64, cfg.max_len, cfg.vocab_size,
                                          seed=1))
        batch = {"tokens": toks[:16]}
        trained = float(m.loss(
            jax.tree_util.tree_map(jnp.asarray, params), batch)[0])
        fresh = float(m.loss(m.init(jax.random.key(0)), batch)[0])
        assert trained < fresh - 0.01, (trained, fresh)

        r = run("tiny", batch=2, seq=32, gen=8,
                ckpt=str(tmp_path / "checkpoints"))
        assert r["ckpt_step"] == step
        assert 0.9 < r["ppl_ratio"] < 1.1
        assert r["max_scale_ratio"] >= 1.0
        assert set(r["per_family_max"]) >= {"qkv", "o", "fc1", "fc2",
                                            "head"}
        s = scale_stats(m.init(jax.random.key(0)), cfg)
        assert s["max_scale_ratio"] >= s["median_scale_ratio"] >= 1.0

        # seq beyond the trained position table must REFUSE, not silently
        # clamp the gather
        with pytest.raises(ValueError, match="position table"):
            run("tiny", batch=2, seq=256, gen=8,
                ckpt=str(tmp_path / "checkpoints"))

    def test_tiny_ppl_ratio_near_one(self):
        """The decode quantization's perplexity damage is bounded: ratio
        within ±2% on the tiny preset (measured ~0.9998; a broken
        scale/dequant path lands far outside)."""
        from dtf_tpu.bench.int8_quality import run

        r = run("tiny", batch=4, seq=64, gen=16)
        assert 0.98 < r["ppl_ratio"] < 1.02
        assert r["tokens_scored"] == 4 * 63
        assert 0.0 <= r["greedy_agreement"] <= 1.0


class TestDecodeLadder:
    @pytest.mark.slow
    def test_ladder_reports_rates(self):
        """The reproducible decode ladder (bench.decode_ladder): positive
        marginal per-token time and consistent aggregate accounting on
        the tiny preset, fused and unfused."""
        from dtf_tpu.bench.decode_ladder import run

        r = run("tiny", mode="fused", streams=2, ladder=(4, 8, 16),
                reps=2)
        assert r["tok_s_per_stream"] is None or r["tok_s_per_stream"] > 0
        if r["tok_s_per_stream"]:
            assert r["tok_s_aggregate"] == pytest.approx(
                2 * r["tok_s_per_stream"])
            # a reported rate must be physically plausible, never the
            # clamped-slope absurdity (time_linfit floors the slope at
            # 1e-12 s)
            assert r["tok_s_per_stream"] < 1e9
        assert len(r["ladder"]) == 3

    @pytest.mark.slow
    def test_no_signal_ladder_flags_warning(self, monkeypatch):
        """A noise-dominated ladder (non-increasing times / clamped
        slope) must yield NO rate, not an absurd one."""
        import dtf_tpu.bench.decode_ladder as dl
        import dtf_tpu.utils.timing as timing

        def flat_fit(fn_of_iters, ladder, reps=3):
            # synthetic clamped-slope fit: no model timing needed
            return timing.LinFit(per_iter_s=1e-12, overhead_s=0.001,
                                 points=tuple((k, 0.001) for k in ladder))

        # decode_ladder imports time_linfit inside run(); patch the source
        monkeypatch.setattr(timing, "time_linfit", flat_fit)
        r = dl.run("tiny", mode="unfused", streams=1, ladder=(4, 8),
                   reps=1)
        assert r["tok_s_per_stream"] is None
        assert "warning" in r

    @pytest.mark.slow
    def test_beam_mode_runs(self):
        from dtf_tpu.bench.decode_ladder import run

        r = run("tiny", mode="unfused", streams=1, beam=2,
                ladder=(4, 8), reps=2)
        assert r["beam"] == 2 and len(r["ladder"]) == 2


class TestKVQuality:
    @pytest.mark.slow
    def test_kv_run_ratio_and_selfcheck(self):
        """KV-cache int8 quality harness: perplexity ratio within a tight
        band on tiny, and the fp-cache decode loss agrees with the same
        positions' parallel-forward loss (the harness's own validity
        check)."""
        from dtf_tpu.bench.int8_quality import kv_run

        r = kv_run("tiny", batch=2, seq=48)
        assert 0.98 < r["kv_ppl_ratio"] < 1.02
        assert abs(r["fp_vs_parallel_delta"]) < 0.05
        assert r["tokens_scored"] == 2 * (48 - 1 - 8)


class TestGradSyncAB:
    def test_ab_structure_and_drop_ratio(self, devices):
        """--grad_sync_ab on the simulated 8-device mesh: all three
        strategies report, the zero1 optimizer-state drop lands near
        (N-1)/N, and no degenerate-mesh warning fires."""
        from dtf_tpu.bench.breakdown import grad_sync_ab

        out = grad_sync_ab(steps=1, batch=64)
        assert out["data_axis"] == 8
        assert "warning" not in out
        assert set(out["strategies"]) == {"dense", "zero1", "zero1_overlap"}
        for row in out["strategies"].values():
            assert row["step_ms"] > 0 and row["grad_sync_ms"] > 0
            assert row["comm_bytes_per_step"] > 0
        assert out["strategies"]["zero1_overlap"]["grad_accum"] == 2
        # overlap's wire bytes scale with its microbatch count
        assert (out["strategies"]["zero1_overlap"]["comm_bytes_per_step"]
                > out["strategies"]["zero1"]["comm_bytes_per_step"])
        assert 0.8 < out["opt_state_drop_ratio"] < 0.95   # ~7/8


class TestBenchLedger:
    """Perf-regression ledger (scripts/bench_ledger.py + bench.py
    --check-ledger, ISSUE 12): the loose BENCH_r*/MULTICHIP_r* round
    files fold into LEDGER.jsonl, and the gate fails loud on a
    regression vs the best prior green run on the same rig."""

    def _ledger_mod(self):
        import importlib
        import os
        import sys
        scripts = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts")
        if scripts not in sys.path:
            sys.path.insert(0, scripts)
        return importlib.import_module("bench_ledger")

    def _rows(self, *vals, rig="TPU v5 lite", errors=()):
        rows = []
        for i, v in enumerate(vals, start=1):
            rows.append({"run": f"BENCH_r{i:02d}", "kind": "bench",
                         "n": i, "commit": None, "rig": rig,
                         "tflops_per_chip": v, "mfu": None,
                         "vs_baseline": None, "ok": v is not None,
                         "error": None if v is not None else "boom",
                         "stage": None if v is not None else "sweep"})
        for i, err in enumerate(errors, start=len(vals) + 1):
            rows.append({"run": f"BENCH_r{i:02d}", "kind": "bench",
                         "n": i, "commit": None, "rig": None,
                         "tflops_per_chip": None, "mfu": None,
                         "vs_baseline": None, "ok": False,
                         "error": err, "stage": "preflight"})
        return rows

    def test_committed_ledger_is_green(self):
        """The acceptance pin: bench.py --check-ledger runs green
        against the COMMITTED LEDGER.jsonl (r01->r02 within tolerance;
        the stalled r03-r05 tpu_unavailable streak prints as a warning,
        not a failure)."""
        import os
        bl = self._ledger_mod()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rows = bl.read_ledger(os.path.join(repo, "LEDGER.jsonl"))
        assert any(r["ok"] and r["tflops_per_chip"] for r in rows)
        ok, lines = bl.check_ledger(rows)
        assert ok, lines
        assert any("STALLED" in ln for ln in lines), lines

    def test_committed_ledger_matches_round_files(self):
        """LEDGER.jsonl is generated, committed state — it must agree
        with rebuilding from the BENCH_r*/MULTICHIP_r* files (commits
        excluded: git metadata is environment-dependent)."""
        import json
        import os
        bl = self._ledger_mod()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        fresh = bl.build_ledger(repo)
        committed = bl.read_ledger(os.path.join(repo, "LEDGER.jsonl"))

        def strip(rows):
            return [{k: v for k, v in r.items() if k != "commit"}
                    for r in rows]

        assert strip(fresh) == strip(committed)

    def test_synthetic_regression_fails(self):
        bl = self._ledger_mod()
        ok, lines = bl.check_ledger(self._rows(193.0, 192.0, 120.0))
        assert not ok
        assert any("REGRESSION" in ln for ln in lines)

    def test_within_tolerance_passes(self):
        bl = self._ledger_mod()
        ok, lines = bl.check_ledger(self._rows(193.0, 185.0))
        assert ok, lines

    def test_first_green_has_no_comparison(self):
        bl = self._ledger_mod()
        ok, lines = bl.check_ledger(self._rows(193.0))
        assert ok
        assert any("no prior to compare" in ln for ln in lines)

    def test_error_rows_do_not_regress_and_streak_warns(self):
        """Error rounds never count as the 'latest green' — the newest
        GREEN run is judged, and a trailing error streak warns."""
        bl = self._ledger_mod()
        rows = self._rows(193.0, 192.0,
                          errors=("tpu_unavailable", "tpu_unavailable"))
        ok, lines = bl.check_ledger(rows)
        assert ok, lines
        assert any("last 2 bench run(s) errored" in ln for ln in lines)

    def test_rigs_compared_independently(self):
        """A slower rig's green run must not read as a regression of a
        faster rig's history."""
        bl = self._ledger_mod()
        rows = self._rows(193.0, 192.0) + self._rows(20.0, rig="cpu")
        # re-number the cpu row after the tpu rows
        rows[-1]["n"] = 3
        rows[-1]["run"] = "BENCH_r03"
        ok, lines = bl.check_ledger(rows)
        assert ok, lines

    def test_plan_round_folds_and_gates(self):
        """PLAN_r*.json (bench.breakdown --plan_ab, ISSUE 19) folds as a
        kind='plan' row gated on wire_reduction, and the committed round
        is green."""
        import os
        bl = self._ledger_mod()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        row = bl.plan_row(os.path.join(repo, "PLAN_r01.json"), repo)
        assert row["kind"] == "plan" and row["ok"]
        assert row["rig"] == "plan_8dev"
        assert 0 < row["wire_reduction"] < 1
        assert row["step_time_ratio"] <= 1.10
        assert row["hbm_prediction_rel_err"] <= 0.05
        ok, lines = bl.check_ledger([row])
        assert ok, lines
        assert any("plan_8dev" in ln for ln in lines)

    def test_plan_gate_failure_names_failing_leg(self, tmp_path):
        """A plan_ab doc whose triple gate failed folds as an errored
        row whose stage names the first failing leg."""
        import json
        bl = self._ledger_mod()
        doc = {"n": 2, "data_axis": 8, "ok": False,
               "wire_win": True, "step_time_ok": False,
               "wire_reduction": 0.1, "step_time_ratio": 1.4,
               "plan_auto": {"hbm_prediction_rel_err": 0.0}}
        p = tmp_path / "PLAN_r02.json"
        p.write_text(json.dumps(doc))
        row = bl.plan_row(str(p), str(tmp_path))
        assert not row["ok"]
        assert row["error"] == "plan_ab_gate_failed"
        assert row["stage"] == "step_time"

    def test_prefix_round_folds_and_gates(self):
        """PREFIX_r*.json (serve_load --prefix_ab, ISSUE 20) folds as a
        kind='prefix' row gated on the cold/warm TTFT p50 ratio, and
        the committed round is green."""
        import os
        bl = self._ledger_mod()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        row = bl.prefix_row(os.path.join(repo, "PREFIX_r01.json"), repo)
        assert row["kind"] == "prefix" and row["ok"]
        assert row["rig"] == "prefix_bs8_p40_n3"
        assert row["ttft_p50_ratio"] >= 1.5        # the A/B's own bar
        assert row["prefix_hit_rate"] > 0
        assert row["leaked_blocks"] == 0
        ok, lines = bl.check_ledger([row])
        assert ok, lines
        assert any("prefix_bs8_p40_n3" in ln for ln in lines)
        # a later round that loses the speedup reads as a REGRESSION
        worse = dict(row, run="PREFIX_r02", n=2, ttft_p50_ratio=1.6)
        ok, lines = bl.check_ledger([row, worse])
        assert not ok
        assert any("REGRESSION" in ln for ln in lines)

    def test_prefix_gate_failure_names_failing_gate(self, tmp_path):
        """A prefix_ab doc whose five-gate verdict failed folds as an
        errored row whose stage names the first failing gate line."""
        import json
        bl = self._ledger_mod()
        doc = {"n": 3, "ok": False, "ttft_p50_ratio": 1.1,
               "rig": "prefix_bs8_p40_n3",
               "cache_on": {"prefix_hit_rate": 0.9, "kv_cached_blocks": 4},
               "churn": {"leaked_on": 0, "leaked_off": 0},
               "gates": ["gate prefix_token_identity: OK — fine",
                         "gate prefix_ttft_p50: FAIL — ratio 1.1 < 1.5"]}
        p = tmp_path / "PREFIX_r03.json"
        p.write_text(json.dumps(doc))
        row = bl.prefix_row(str(p), str(tmp_path))
        assert not row["ok"]
        assert row["error"] == "prefix_ab_gate_failed"
        assert row["stage"] == "prefix_ttft_p50"

    def test_check_ledger_cli_green_and_regression(self, tmp_path):
        """python bench.py --check-ledger end to end: green on the
        committed ledger, exit 1 when a synthetic regression row is
        appended (the falsifiability half)."""
        import json
        import os
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        bench = os.path.join(repo, "bench.py")
        r = subprocess.run([sys.executable, bench, "--check-ledger"],
                           capture_output=True, text=True, timeout=60,
                           cwd=repo)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "ledger check: OK" in r.stdout
        rows = [json.loads(ln) for ln in
                open(os.path.join(repo, "LEDGER.jsonl"))]
        rows.append({"run": "BENCH_r99", "kind": "bench", "n": 99,
                     "commit": None, "rig": "TPU v5 lite",
                     "tflops_per_chip": 100.0, "mfu": 0.5,
                     "vs_baseline": 0.56, "ok": True, "error": None,
                     "stage": None})
        bad = tmp_path / "LEDGER.jsonl"
        with open(bad, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
        r = subprocess.run([sys.executable, bench, "--check-ledger",
                            "--ledger", str(bad)],
                           capture_output=True, text=True, timeout=60,
                           cwd=repo)
        assert r.returncode == 1
        assert "REGRESSION" in r.stdout
