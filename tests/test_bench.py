"""Matmul benchmark tests on the simulated 8-device mesh (SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from dtf_tpu.bench.matmul import (
    MatmulBenchConfig, make_operands, run_matmul_bench, verify_correctness,
    peak_flops_per_chip, _operand_shardings,
)
from dtf_tpu.parallel.mesh import make_mesh


class TestMatmulBench:
    def test_correctness_sharded_1d(self, mesh8):
        err = verify_correctness(mesh8, n=128)
        assert err < 1e-3

    def test_correctness_sharded_2d(self, mesh_2d):
        """The '2-worker PS matmul -> ICI mesh' config (BASELINE.md row 2),
        generalized: A rows on data, B cols on tensor."""
        err = verify_correctness(mesh_2d, n=128)
        assert err < 1e-3

    def test_operand_shardings(self, mesh_2d):
        a_sh, b_sh = _operand_shardings(mesh_2d)
        from jax.sharding import PartitionSpec as P
        assert a_sh.spec == P(("data",), None)
        assert b_sh.spec == P(None, "tensor")

    def test_bench_runs_and_reports(self, mesh8):
        cfg = MatmulBenchConfig(n=64, mesh=mesh8, dtype="float32",
                                target_long_s=0.05, reps=1)
        r = run_matmul_bench(cfg)
        assert r["n_chips"] == 8
        assert r["matmul_time_us"] > 0
        assert r["tflops_per_chip"] > 0
        # CPU has no roofline entry.
        assert r["peak_tflops_per_chip"] is None

    def test_operands_deterministic(self, mesh8):
        a1, b1 = make_operands(mesh8, 64, "float32", seed=1)
        a2, b2 = make_operands(mesh8, 64, "float32", seed=1)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))

    def test_peak_table_unknown_device_none(self):
        assert peak_flops_per_chip(jax.devices()[0]) is None  # CPU


class TestInt8Quality:
    def test_tiny_ppl_ratio_near_one(self):
        """The decode quantization's perplexity damage is bounded: ratio
        within ±2% on the tiny preset (measured ~0.9998; a broken
        scale/dequant path lands far outside)."""
        from dtf_tpu.bench.int8_quality import run

        r = run("tiny", batch=4, seq=64, gen=16)
        assert 0.98 < r["ppl_ratio"] < 1.02
        assert r["tokens_scored"] == 4 * 63
        assert 0.0 <= r["greedy_agreement"] <= 1.0
