"""Fault-tolerant serving fleet (dtf_tpu/serve/fleet.py, ISSUE 16).

Wall-clock socket tests against an in-process local fleet: routing +
fleet-unique rid minting, replica-down failover with TOKEN-IDENTICAL
replay (the client's stream is bitwise the uninterrupted single-engine
reference), hedged dispatch (single winning stream, loser's KV blocks
freed — the pool-leak pin), wedge detection via the stream timeout,
conn-flake transience, rolling drain into the ``drain.r<k>.jsonl``
namespace, acceptor-level brownout shedding (two-tier accounting), the
drain-merge collision guard, and reqtrace continuity across a failover
(one trace id spans both replicas, the replay submit marked
``resubmit``).

Every fleet here runs on the REAL wire (line-JSON TCP legs, one driver
thread stepping all engines) — only the reference arm uses the virtual
clock.  Temperature is pinned to 0 so token identity is a greedy-decode
invariant, independent of rid assignment order.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from dtf_tpu.resilience.chaos import FaultPlan
from dtf_tpu.serve import ServingEngine, VirtualClock
from dtf_tpu.serve.fleet import (FleetAcceptor, FleetConfig, Replica,
                                 build_local_fleet, client_summary,
                                 drive_trace, merge_drain_docs,
                                 read_drain_files)

pytestmark = pytest.mark.serve

#: one engine shape for every arm — identity comparisons need it equal
ENGINE_KW = dict(num_slots=2, block_size=4, blocks_per_slot=8,
                 max_queue=64)


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    from dtf_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig.tiny())
    return model, model.init(jax.random.key(0))


def _cfg(**kw):
    kw.setdefault("stream_timeout_s", 5.0)
    kw.setdefault("connect_timeout_s", 2.0)
    kw.setdefault("beat_stale_s", 10.0)
    kw.setdefault("monitor_interval_s", 0.05)
    return FleetConfig(**kw)


def _fleet(model, params, n, **kw):
    kw.setdefault("config", _cfg())
    kw.setdefault("engine_kwargs", dict(ENGINE_KW))
    return build_local_fleet(model, params, n, seed=0, **kw).start()


def _trace(n, *, qps=100.0, max_new=8, p_len=4, vocab=128, seed=0,
           **extra):
    rng = np.random.default_rng(seed)
    out, t = [], 0.0
    for i in range(n):
        t += float(rng.exponential(1.0)) / qps
        out.append((t, {"rid": i,
                        "prompt": [int(x) for x in
                                   rng.integers(0, vocab, (p_len,))],
                        "max_new_tokens": max_new, "temperature": 0.0,
                        **extra}))
    return out


def _reference_tokens(model, params, trace):
    """The uninterrupted ground truth: ONE virtual-clock engine, same
    seed and shape, same trace — trace index -> token list."""
    eng = ServingEngine(model, params, seed=0, clock=VirtualClock(),
                        **ENGINE_KW)
    eng.run([(t, {**kw, "prompt": np.asarray(kw["prompt"], np.int32)})
             for t, kw in trace])
    return {rid: list(req.tokens) for rid, req in eng.results.items()}


def _assert_identical(res, ref):
    for i, rec in res.items():
        assert rec["status"] == "completed", (i, rec["status"])
        assert rec["tokens"] == ref[i], f"request {i} diverged"


# ---------------------------------------------------------------------------
# routing + fleet-unique rids
# ---------------------------------------------------------------------------


class TestFleetServes:
    def test_routes_completes_token_identity_and_unique_rids(
            self, tiny_model):
        model, params = tiny_model
        trace = _trace(6)
        ref = _reference_tokens(model, params, trace)
        acc = _fleet(model, params, 2)
        try:
            res = drive_trace(acc.address, trace, request_timeout_s=60.0)
            _assert_identical(res, ref)
            cs = client_summary(res, slo_ttft_ms=10_000.0)
            assert cs["completed"] == 6 and cs["lost"] == 0
            # the rid-collision fix: the acceptor mints fleet-unique
            # rids, so the two engines' result namespaces are DISJOINT
            r0 = set(acc.replicas[0].engine.results)
            r1 = set(acc.replicas[1].engine.results)
            assert not (r0 & r1)
            assert len(r0 | r1) == 6
            # acceptor control line: the /fleetz rollup over the wire
            import socket as _socket
            with _socket.create_connection(acc.address, timeout=5.0) as s:
                s.sendall(b'{"stats": true}\n')
                doc = json.loads(s.makefile("rb").readline())
            assert doc["ok"] and len(doc["fleet"]["replicas"]) == 2
            assert doc["fleet"]["totals"]["completed"] == 6
        finally:
            acc.shutdown()


# ---------------------------------------------------------------------------
# replica failure domains: kill, wedge, flake
# ---------------------------------------------------------------------------


class TestFailover:
    def test_replica_down_replays_token_identically(self, tiny_model):
        model, params = tiny_model
        trace = _trace(8, qps=200.0, max_new=16)
        ref = _reference_tokens(model, params, trace)
        acc = _fleet(model, params, 2)
        try:
            acc.arm_chaos(FaultPlan.parse("replica_down@2:0",
                                          process_index=0))
            res = drive_trace(acc.address, trace, request_timeout_s=60.0)
            _assert_identical(res, ref)          # bitwise, across the kill
            t = acc.totals()
            assert t["failovers"] >= 1 and t["replayed"] >= 1
            assert acc.replicas[0].state == "down"
            assert acc.replicas[0].down_reason == "chaos_kill"
            assert client_summary(res, slo_ttft_ms=10_000.0)["lost"] == 0
        finally:
            acc.shutdown()

    def test_wedged_replica_fails_over_via_stream_timeout(
            self, tiny_model):
        """A wedge is the nasty failure mode: the socket ACCEPTS but the
        engine never steps — detection must come from the response-stream
        timeout, not a clean connection error."""
        model, params = tiny_model
        trace = _trace(1, max_new=8)
        ref = _reference_tokens(model, params, trace)
        acc = _fleet(model, params, 2,
                     config=_cfg(stream_timeout_s=1.5))
        try:
            acc.arm_chaos(FaultPlan.parse("replica_wedge@1:8000ms:0",
                                          process_index=0))
            res = drive_trace(acc.address, trace, request_timeout_s=60.0)
            _assert_identical(res, ref)
            assert acc.totals()["failovers"] >= 1
        finally:
            acc.shutdown()

    def test_conn_flake_is_transient(self, tiny_model):
        """A severed acceptor<->replica socket fails the leg over but the
        replica STAYS in rotation — flake != death."""
        model, params = tiny_model
        # arrivals ~20ms apart with long streams: by dispatch 3 the
        # first legs are established and mid-stream, so the severed
        # socket provably interrupts live work (no admission race)
        trace = _trace(6, qps=50.0, max_new=24)
        ref = _reference_tokens(model, params, trace)
        acc = _fleet(model, params, 2)
        try:
            acc.arm_chaos(FaultPlan.parse("conn_flake@3:0",
                                          process_index=0))
            res = drive_trace(acc.address, trace, request_timeout_s=60.0)
            _assert_identical(res, ref)
            assert acc.totals()["failovers"] >= 1
            assert acc.replicas[0].state == "up"
        finally:
            acc.shutdown()

    def test_failover_trace_continuity(self, tiny_model, tmp_path):
        """ISSUE 16 observability pin: a failed-over request's reqtrace
        chain spans BOTH replicas under ONE trace id — two submit
        events, the replay's marked ``resubmit`` — and completeness over
        the whole run stays 1.0 (failover does not cost attribution)."""
        from dtf_tpu import telemetry as tel
        from dtf_tpu.telemetry import reqtrace

        tel.configure(str(tmp_path))
        model, params = tiny_model
        # spaced arrivals + long streams: by dispatch 3 the first
        # request is mid-decode on replica 0 (its submit span already
        # emitted THERE), so the kill provably splits a live trace
        # across the two replicas
        trace = _trace(6, qps=50.0, max_new=24)
        acc = _fleet(model, params, 2)
        try:
            acc.arm_chaos(FaultPlan.parse("replica_down@3:0",
                                          process_index=0))
            res = drive_trace(acc.address, trace, request_timeout_s=60.0)
        finally:
            acc.shutdown()
        assert acc.totals()["replayed"] >= 1
        assert all(r["status"] == "completed" for r in res.values())
        tel.get_tracer().flush()
        traces = reqtrace.group_traces(
            reqtrace.load_request_events(str(tmp_path)))
        comp = reqtrace.completeness(traces)
        assert comp["completed"] >= 6
        assert comp["complete_frac"] == 1.0, comp["incomplete"]
        replayed = [evs for evs in traces.values()
                    if sum(e["phase"] == "submit" for e in evs) >= 2]
        assert replayed, "no trace spans the failover"
        assert any(e.get("resubmit") for evs in replayed for e in evs
                   if e["phase"] == "submit")


# ---------------------------------------------------------------------------
# rid supersede: ONE live request per rid per engine
# ---------------------------------------------------------------------------


class TestRidSupersede:
    def test_resubmitted_rid_supersedes_live_copy(self, tiny_model):
        """A failover/hedge replay can resubmit a rid whose earlier copy
        is still LIVE on the target engine — the leg's cancel races the
        resubmit through the frontend mailbox.  The new submission must
        tear the stale copy out first: two live requests under one rid
        cross-wire their token streams into the bridge's per-rid queue
        and the acceptor's replay-prefix verification (correctly) fails
        the request (found by a fleet chaos drive)."""
        model, params = tiny_model
        prompt = np.arange(4, dtype=np.int32)
        ref_eng = ServingEngine(model, params, seed=0,
                                clock=VirtualClock(), **ENGINE_KW)
        ref = ref_eng.submit(prompt, 8, rid=9)
        for _ in range(100):
            if ref.status == "completed":
                break
            ref_eng.step()
        assert ref.status == "completed"

        eng = ServingEngine(model, params, seed=0, clock=VirtualClock(),
                            **ENGINE_KW)
        old = eng.submit(prompt, 8, rid=9)
        for _ in range(4):            # admit + prefill + a few decodes
            eng.step()
        assert old.status == "running" and len(old.tokens) >= 1
        new = eng.submit(prompt, 8, rid=9, resubmit=True)
        assert old.status == "cancelled"       # stale copy torn out
        for _ in range(100):
            if new.status == "completed":
                break
            eng.step()
        assert new.status == "completed"
        assert list(new.tokens) == list(ref.tokens)  # fresh full stream


# ---------------------------------------------------------------------------
# hedged dispatch
# ---------------------------------------------------------------------------


class TestHedging:
    def test_hedge_single_stream_and_no_pool_leak(self, tiny_model):
        """The double-emit / leaked-KV pin: with hedging forced on every
        request, the client still sees EXACTLY ONE stream per request
        and, once quiesced, every allocator is back to its pre-run free
        count — the cancelled loser's blocks came home."""
        model, params = tiny_model
        trace = _trace(4, qps=300.0, max_new=8, priority=1)
        ref = _reference_tokens(model, params, trace)
        acc = _fleet(model, params, 2,
                     config=_cfg(hedge_priority=1, hedge_delay_ms=1.0))
        free0 = [r.engine.scheduler.allocator.free_blocks
                 for r in acc.replicas]
        try:
            res = drive_trace(acc.address, trace, request_timeout_s=60.0)
            _assert_identical(res, ref)
            for rec in res.values():
                assert len(rec["tokens"]) == 8      # one stream, no dupes
            t = acc.totals()
            assert t["hedged"] >= 1
            assert t["hedge_wins"] + t["hedge_cancelled"] >= 1
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                free = [r.engine.scheduler.allocator.free_blocks
                        for r in acc.replicas]
                if free == free0:
                    break
                time.sleep(0.02)
            assert free == free0, f"leaked KV blocks: {free} != {free0}"
        finally:
            acc.shutdown()


# ---------------------------------------------------------------------------
# rolling drain
# ---------------------------------------------------------------------------


class TestRollingDrain:
    def test_drain_replica_namespaces_and_fails_over(self, tiny_model,
                                                     tmp_path):
        model, params = tiny_model
        trace = _trace(12, qps=400.0, max_new=16)
        acc = _fleet(model, params, 2, logdir=str(tmp_path))
        try:
            box = {}

            def run():
                box["res"] = drive_trace(acc.address, trace,
                                         request_timeout_s=60.0)

            th = threading.Thread(target=run, daemon=True)
            th.start()
            # wait for replica 0 to actually hold work, then drain it
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                if (acc.replicas[0].inflight > 0
                        or acc.replicas[0].engine.scheduler.has_work()):
                    break
                time.sleep(0.005)
            acc.drain_replica(0)
            th.join(timeout=60.0)
            assert not th.is_alive()
            res = box["res"]
            assert all(r["status"] == "completed" for r in res.values())
            assert client_summary(res,
                                  slo_ttft_ms=10_000.0)["lost"] == 0
            assert acc.replicas[0].state == "down"
            assert acc.replicas[0].down_reason == "drained"
            # the namespace fix: per-replica drain files, and the merged
            # read is collision-checked
            path = tmp_path / "drain.r0.jsonl"
            if path.exists():                     # queued work remained
                docs = read_drain_files(str(tmp_path))
                assert all("rid" in d for d in docs)
        finally:
            acc.shutdown()


# ---------------------------------------------------------------------------
# acceptor-level brownout (two-tier shed)
# ---------------------------------------------------------------------------


class TestFleetBrownout:
    def _acceptor(self):
        return FleetAcceptor([Replica(0, ("127.0.0.1", 1)),
                              Replica(1, ("127.0.0.1", 2))])

    def test_sheds_low_priority_when_all_replicas_degraded(self):
        acc = self._acceptor()
        try:
            for r in acc.replicas:
                r.stats = {"brownout_level": 2}
            parsed = {"trace_id": "t-low", "priority": 0}
            fl, shed = acc._admit({}, parsed)
            assert shed is not None
            assert shed["status"] == "shed_fleet_brownout"
            # latency-critical traffic still admits (two-tier: the
            # replicas' own brownout governs it from here)
            fl, shed = acc._admit({}, {"trace_id": "t-hi", "priority": 1})
            assert shed is None
        finally:
            acc.server.server_close()

    def test_one_degraded_replica_does_not_brown_out_fleet(self):
        acc = self._acceptor()
        try:
            acc.replicas[0].stats = {"brownout_level": 3}
            acc.replicas[1].stats = {"brownout_level": 0}
            fl, shed = acc._admit({}, {"trace_id": "t", "priority": 0})
            assert shed is None
        finally:
            acc.server.server_close()

    def test_sheds_everything_with_no_live_replicas(self):
        acc = self._acceptor()
        try:
            for r in acc.replicas:
                r.state = "down"
            fl, shed = acc._admit({}, {"trace_id": "t", "priority": 5})
            assert shed is not None
            assert shed["status"] == "shed_fleet_no_replicas"
        finally:
            acc.server.server_close()


# ---------------------------------------------------------------------------
# drain-merge collision guard
# ---------------------------------------------------------------------------


class TestMergeDrainDocs:
    def test_disjoint_namespaces_merge_sorted(self):
        out = merge_drain_docs([[{"rid": 3}, {"rid": 1}],
                                [{"rid": 2}]])
        assert [d["rid"] for d in out] == [1, 2, 3]

    def test_collision_fails_loudly(self):
        with pytest.raises(ValueError, match="rid collision"):
            merge_drain_docs([[{"rid": 0}], [{"rid": 0}]])

    def test_read_drain_files_roundtrip(self, tmp_path):
        for k, rids in ((0, [0, 2]), (1, [1, 5])):
            with open(tmp_path / f"drain.r{k}.jsonl", "w") as f:
                for rid in rids:
                    f.write(json.dumps({"rid": rid, "prompt": [1]}) + "\n")
        docs = read_drain_files(str(tmp_path))
        assert [d["rid"] for d in docs] == [0, 1, 2, 5]

    def test_read_drain_files_collision(self, tmp_path):
        for k in (0, 1):
            with open(tmp_path / f"drain.r{k}.jsonl", "w") as f:
                f.write(json.dumps({"rid": 7}) + "\n")
        with pytest.raises(ValueError, match="rid collision"):
            read_drain_files(str(tmp_path))
