"""Multi-process distributed rig: N local processes, jax.distributed
coordination service over localhost — the DCN bootstrap path, exercised the
way the reference exercised its gRPC cluster (SURVEY.md §4 'Multi-process').

Each child process simulates 4 CPU devices, so 2 processes form a global
8-device mesh; the MNIST workload runs data-parallel across them with the
reference CLI (--job_name/--task_index + coordinator flags)."""

import os
import socket
import subprocess
import sys

import pytest


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child_env(n_local_devices: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_local_devices}")
    # Drop sitecustomize shim dirs (e.g. the TPU-relay shim) from the child
    # path: a sitecustomize that imports jax initializes the backend before
    # main() runs, which silently breaks jax.distributed.initialize — each
    # child would come up as a single-process job.
    inherited = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))]
    env["PYTHONPATH"] = os.pathsep.join([REPO_ROOT, *inherited])
    return env


def run_workers(cmds, *, n_local_devices: int, cwd=None,
                timeout: int = 420) -> list:
    """Spawn one child per command, wait for all, assert every exit code is
    0, always kill stragglers.  Returns each task's combined output."""
    procs = [subprocess.Popen(
        cmd, cwd=cwd, env=child_env(n_local_devices),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for cmd in cmds]
    outs = []
    try:
        for task, p in enumerate(procs):
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
            if ("Multiprocess computations aren't implemented on the CPU "
                    "backend" in out):
                # Old jaxlib CPU backends have the coordination service
                # but no cross-process device collectives — the rig
                # cannot run at all there (environment, not a product
                # regression).
                pytest.skip("this jaxlib's CPU backend has no multiprocess "
                            "collectives")
            assert p.returncode == 0, f"task {task} failed:\n{out[-3000:]}"
    finally:
        for p in procs:   # never leak hung distributed workers
            if p.poll() is None:
                p.kill()
    return outs


@pytest.mark.slow
class TestMultiProcess:
    def test_two_process_mnist_data_parallel(self, tmp_path):
        """2 processes x 4 simulated devices: full DP MNIST epoch over the
        coordination service; both exit 0, coordinator logs eval."""
        port = free_port()
        outs = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.mnist",
              "--job_name", "worker", "--task_index", str(task),
              "--coordinator_address", f"localhost:{port}",
              "--num_processes", "2", "--mesh", "data=-1",
              "--epochs", "1", "--batch_size", "128",
              "--log_frequency", "50",
              "--logdir", str(tmp_path / f"logs{task}")]
             for task in range(2)],
            n_local_devices=4, cwd=tmp_path)
        # coordinator (task 0) owns the console contract
        assert "Test-Accuracy" in outs[0]
        assert "done" in outs[0]
        # non-coordinator stays silent on the log contract (SPMD: only
        # process 0 prints, SURVEY.md §7 'multi-host SPMD mental model')
        assert "Test-Accuracy" not in outs[1]

    def test_sharded_data_trajectory_matches_single_process(self, tmp_path):
        """cfg.shard_data (the multi-process default): each host feeds only
        its contiguous slice of every global batch (ProcessShard +
        put_process_batch).  The optimization trajectory must be IDENTICAL
        to one process feeding full global batches — same final cost and
        test accuracy to every printed digit."""
        import re

        single = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.mnist",
              "--epochs", "1", "--batch_size", "128",
              "--log_frequency", "50",
              "--logdir", str(tmp_path / "single")]],
            n_local_devices=8, cwd=tmp_path)
        port = free_port()
        duo = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.mnist",
              "--task_index", str(task),
              "--coordinator_address", f"localhost:{port}",
              "--num_processes", "2", "--mesh", "data=-1",
              "--epochs", "1", "--batch_size", "128",
              "--log_frequency", "50",
              "--logdir", str(tmp_path / f"duo{task}")]
             for task in range(2)],
            n_local_devices=4, cwd=tmp_path)

        def metrics(out):
            cost = re.search(r"Final Cost: ([0-9.]+)", out)
            acc = re.search(r"Test-Accuracy: ([0-9.]+)", out)
            assert cost and acc, out[-2000:]
            return cost.group(1), acc.group(1)

        assert metrics(single[0]) == metrics(duo[0])

    def test_zero1_two_process_matches_single_process_dense(self, tmp_path):
        """ISSUE 5 acceptance: --grad_sync zero1 on a 2-process simulated
        mesh (reduce-scatter/all-gather hops cross the DCN boundary) must
        track the single-process DENSE trajectory — same seed, same
        batches; cost/accuracy within float tolerance (collective
        reduction orders differ, so not digit-exact like the pure
        data-path A/B above)."""
        import re

        single = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.mnist",
              "--epochs", "1", "--batch_size", "128", "--init", "fan_in",
              "--optimizer", "adam", "--learning_rate", "1e-3",
              "--log_frequency", "50",
              "--logdir", str(tmp_path / "single")]],
            n_local_devices=8, cwd=tmp_path)
        port = free_port()
        duo = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.mnist",
              "--task_index", str(task),
              "--coordinator_address", f"localhost:{port}",
              "--num_processes", "2", "--mesh", "data=-1",
              "--grad_sync", "zero1", "--grad_bucket_mb", "0.1",
              "--epochs", "1", "--batch_size", "128", "--init", "fan_in",
              "--optimizer", "adam", "--learning_rate", "1e-3",
              "--log_frequency", "50",
              "--logdir", str(tmp_path / f"duo{task}")]
             for task in range(2)],
            n_local_devices=4, cwd=tmp_path)

        def metrics(out):
            cost = re.search(r"Final Cost: ([0-9.]+)", out)
            acc = re.search(r"Test-Accuracy: ([0-9.]+)", out)
            assert cost and acc, out[-2000:]
            return float(cost.group(1)), float(acc.group(1))

        c_single, a_single = metrics(single[0])
        c_duo, a_duo = metrics(duo[0])
        assert abs(c_single - c_duo) < 5e-3, (c_single, c_duo)
        assert abs(a_single - a_duo) < 2e-2, (a_single, a_duo)

    def test_int8_ring_crosses_process_boundary(self, tmp_path):
        """The quantized ring's ppermute hops span the 2-process mesh: the
        explicit int8 gradient sync must work over the DCN path too."""
        port = free_port()
        outs = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.mnist",
              "--job_name", "worker", "--task_index", str(task),
              "--coordinator_address", f"localhost:{port}",
              "--num_processes", "2", "--mesh", "data=-1",
              "--mode", "explicit", "--grad_compression", "int8",
              "--epochs", "1", "--batch_size", "512",
              "--log_frequency", "100",
              "--logdir", str(tmp_path / f"logs{task}")]
             for task in range(2)],
            n_local_devices=2, cwd=tmp_path)
        assert "Test-Accuracy" in outs[0]

    def test_pipeline_spans_processes(self, tmp_path):
        """A pipe=2 x data=4 mesh over 2 processes, pipe as the SLOWEST
        axis so each process holds one full stage: the pipeline's
        stage-to-stage ppermute hops cross the process boundary (DCN path)
        inside the BERT train step."""
        port = free_port()
        outs = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.bert_pretrain",
              "--task_index", str(task),
              "--coordinator_address", f"localhost:{port}",
              "--num_processes", "2", "--mesh", "pipe=2,data=4",
              "--preset", "tiny", "--steps", "4", "--batch_size", "16",
              "--pipeline_microbatches", "2", "--log_frequency", "2",
              "--logdir", str(tmp_path / f"logs{task}")]
             for task in range(2)],
            n_local_devices=4, cwd=tmp_path)
        assert "Step-Time" in outs[0]
        assert "done" in outs[0]

    def test_sequence_parallel_spans_processes(self, tmp_path):
        """A data=2 x seq=2 mesh over 2 processes: ulysses all-to-alls run
        across the process boundary inside the BERT train step."""
        port = free_port()
        outs = run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.bert_pretrain",
              "--task_index", str(task),
              "--coordinator_address", f"localhost:{port}",
              "--num_processes", "2", "--mesh", "data=2,seq=2",
              "--preset", "tiny", "--steps", "3", "--batch_size", "8",
              "--ulysses", "--logdir", str(tmp_path / f"logs{task}")]
             for task in range(2)],
            n_local_devices=2, cwd=tmp_path)
        assert "Step-Time" in outs[0]

    def test_preemption_agrees_across_processes(self, tmp_path):
        """SIGTERM both processes mid-run: the allgather at the logging
        sync boundary makes them checkpoint the SAME step and exit 0
        (utils/preemption.py 'agreed')."""
        import signal
        import time

        port = free_port()
        procs = []
        for task in range(2):
            cmd = [
                sys.executable, "-m", "dtf_tpu.workloads.mnist",
                "--task_index", str(task),
                "--coordinator_address", f"localhost:{port}",
                "--num_processes", "2", "--mesh", "data=-1",
                "--epochs", "50", "--batch_size", "256",
                "--log_frequency", "5",
                "--checkpoint_every", "1000000",   # only preemption saves
                "--logdir", str(tmp_path / "shared"),
            ]
            procs.append(subprocess.Popen(
                cmd, cwd=tmp_path, env=child_env(2),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
        try:
            # wait for training to demonstrably progress on the coordinator
            # (select-based: a silently-wedged child must hit the deadline,
            # not block forever in readline)
            import select
            deadline = time.time() + 300
            pre = []
            while time.time() < deadline:
                ready, _, _ = select.select([procs[0].stdout], [], [], 5)
                if not ready:
                    continue
                line = procs[0].stdout.readline()
                if not line:
                    break
                pre.append(line)
                if line.startswith("Step: "):
                    break
            for p in procs:
                p.send_signal(signal.SIGTERM)
            outs = []
            for task, p in enumerate(procs):
                out, _ = p.communicate(timeout=300)
                outs.append(out)
                assert p.returncode == 0, \
                    f"task {task} failed:\n{out[-3000:]}"
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        text = "".join(pre) + outs[0]
        assert "preempted: checkpointed step" in text, text[-2000:]
        ckpts = [d for d in os.listdir(str(tmp_path / "shared/checkpoints"))
                 if d.isdigit()]
        assert len(ckpts) == 1, f"expected one agreed step, got {ckpts}"

    def test_ps_job_name_compat_shim(self, tmp_path):
        """--job_name=ps joins as a peer (no PS role in an all-reduce
        design, cluster.py docstring): the 2-process job still completes
        with one 'ps' and one 'worker'."""
        port = free_port()
        run_workers(
            [[sys.executable, "-m", "dtf_tpu.workloads.mnist",
              "--job_name", job, "--task_index", str(task),
              "--coordinator_address", f"localhost:{port}",
              "--num_processes", "2", "--mesh", "data=-1",
              "--epochs", "1", "--batch_size", "512",
              "--log_frequency", "100",
              "--logdir", str(tmp_path / f"logs{task}")]
             for task, job in ((0, "worker"), (1, "ps"))],
            n_local_devices=2, cwd=tmp_path)

    # Cluster failure schedule for the ISSUE-2 scenarios: host 1 dies
    # abruptly (SIGKILL) before step 8; per-step pacing keeps host 0
    # demonstrably mid-run when the loss is detected (and makes host 1 a
    # flagged straggler while it lives).
    # Timeline after the lockstep barrier: host 1 (100ms/step) dies at
    # its step 20 (~2s) — after host 0 (250ms/step) commits its step-5
    # checkpoint (~1.3s), before either host's 30-step budget completes.
    _HOST_DOWN_CHAOS = ("slow_host@0:0:250ms,slow_host@0:1:100ms,"
                        "host_down@20:1")

    @pytest.mark.chaos
    def test_host_down_coordinated_abort(self, tmp_path):
        """THE ISSUE-2 acceptance bar, detection half: host_down@20:1
        kills process 1 abruptly (SIGKILL, no goodbye) mid-run.  Process 0 must
        be freed by the health monitor's poison-pill coordinated abort
        (exit 71) within the heartbeat budget — NOT run to its own
        timeout, and NOT exit cleanly."""
        import signal
        import time

        driver = os.path.join(REPO_ROOT, "tests", "_mp_health.py")
        shared = str(tmp_path / "shared")
        t0 = time.monotonic()
        procs = [subprocess.Popen(
            [sys.executable, driver, str(task), "2", shared, "2000", "4",
             self._HOST_DOWN_CHAOS],
            cwd=tmp_path, env=child_env(4),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
            for task in range(2)]
        try:
            outs = [p.communicate(timeout=300)[0] for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        elapsed = time.monotonic() - t0
        # task 1 died by its own SIGKILL; task 0 took the coordinated
        # abort exit, with the poison pill and stack dump on record.
        assert procs[1].returncode in (-signal.SIGKILL,
                                       128 + signal.SIGKILL), \
            f"task 1 should die by SIGKILL:\n{outs[1][-2000:]}"
        assert procs[0].returncode == 71, \
            f"task 0 should exit EXIT_PEER_LOST(71), got " \
            f"{procs[0].returncode}:\n{outs[0][-3000:]}"
        assert "HEALTH" in outs[0] and "missed" in outs[0], outs[0][-2000:]
        assert os.path.exists(os.path.join(shared, "health", "poison.json"))
        # "within the heartbeat budget": max_steps=2000 means task 0 can
        # ONLY exit through the abort; the whole run (jax startup + a few
        # paced steps + detection) lands far below the rig timeout.
        assert elapsed < 240, f"abort took {elapsed:.0f}s — wedged?"

    @pytest.mark.chaos
    def test_elastic_restart_resumes_on_survivor(self, tmp_path):
        """THE ISSUE-2 acceptance bar, recovery half: a 2-host run loses
        host 1; run_elastic_hosts relaunches the SURVIVOR as a 1-host job
        on a SHRUNKEN mesh (4 -> 2 devices), which reshards the last
        intact checkpoint through the restore template and finishes —
        with the SAME final loss as a fault-free run (trajectory
        invariance across the shrink)."""
        import re

        from dtf_tpu.resilience.supervisor import run_elastic_hosts

        driver = os.path.join(REPO_ROOT, "tests", "_mp_health.py")
        shared = str(tmp_path / "shared")

        def build_cmd(slot, n_hosts, round_idx):
            chaos = self._HOST_DOWN_CHAOS if round_idx == 0 else ""
            devices = "4" if round_idx == 0 else "2"
            return [sys.executable, driver, str(slot), str(n_hosts),
                    shared, "30", devices, chaos]

        outs, n_final, rounds = run_elastic_hosts(
            build_cmd, 2, max_rounds=2, env=child_env(4),
            cwd=str(tmp_path), timeout_s=300)
        assert (n_final, rounds) == (1, 1), (n_final, rounds, outs)
        done = re.search(r"MP_HEALTH_DONE steps=(\d+) "
                         r"final_cost=([0-9.]+)", outs[0])
        assert done, outs[0][-3000:]
        assert int(done.group(1)) == 30
        assert "resumed from step" in outs[0], outs[0][-3000:]

        # Fault-free reference over the same trajectory (the restart
        # resumed the last intact checkpoint of the SAME trajectory, so
        # the two runs coincide step-for-step).
        ref = subprocess.run(
            [sys.executable, driver, "0", "1", str(tmp_path / "ref"),
             "30", "2", ""],
            cwd=tmp_path, env=child_env(4), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, timeout=300)
        assert ref.returncode == 0, ref.stdout[-3000:]
        ref_done = re.search(r"MP_HEALTH_DONE steps=(\d+) "
                             r"final_cost=([0-9.]+)", ref.stdout)
        assert ref_done, ref.stdout[-3000:]
        assert abs(float(done.group(2))
                   - float(ref_done.group(2))) < 2e-3, \
            f"elastic-restart loss {done.group(2)} != fault-free " \
            f"{ref_done.group(2)}"

    @pytest.mark.chaos
    @pytest.mark.scenarios
    def test_elastic_restart_zero1_transformer(self, tmp_path):
        """Elastic 4 -> 2 restart under --grad_sync zero1 on a
        TRANSFORMER workload (ISSUE-8 satellite: the acceptance pair
        above only covers the MLP path).  A 2-host tiny-GPT cell with
        ZeRO-1 sharded optimizer state loses host 1 mid-run; the
        relaunch reshards the bucketed opt state onto the shrunken mesh
        (PR 5's N-stable padding) and must finish with the SAME final
        loss as a fault-free run of the same trajectory."""
        import json
        import re

        from dtf_tpu.resilience.supervisor import run_elastic_hosts
        from dtf_tpu.scenarios.spec import Gate, ScenarioSpec

        # Same timing discipline as the scenario matrix's elastic cell:
        # host 1 (100ms/step) dies at its step 12 (~1.2s past the
        # lockstep barrier) while host 0 (250ms/step pacing, 40-step
        # budget ~11s) is reliably MID-run when the loss is detected
        # (~5s) — the abort must interrupt training, not lose a race
        # with completion.
        spec = ScenarioSpec(
            name="gpt_zero1_elastic", workload="gpt", hosts=2,
            devices=4, shrink_devices=2, grad_sync="zero1",
            steps=40, batch_size=16, learning_rate=3e-3,
            checkpoint_every=4, log_frequency=4,
            chaos=("slow_host@0:0:250ms,slow_host@0:1:100ms,"
                   "host_down@12:1"),
            gate=Gate(max_final_cost=10.0, min_goodput=0.0))
        shared = str(tmp_path / "shared")

        def build_cmd(slot, n_hosts, round_idx):
            chaos = spec.chaos if round_idx == 0 else ""
            devices = spec.devices if round_idx == 0 \
                else spec.shrink_devices
            return [sys.executable, "-m", "dtf_tpu.scenarios._host",
                    spec.to_json(), str(slot), str(n_hosts), shared,
                    str(devices), chaos]

        outs, n_final, rounds = run_elastic_hosts(
            build_cmd, 2, max_rounds=2, env=child_env(4),
            cwd=str(tmp_path), timeout_s=360)
        assert (n_final, rounds) == (1, 1), (n_final, rounds, outs)
        done = re.search(r"SCENARIO_DONE steps=(\d+) "
                         r"final_cost=([0-9.]+)", outs[0])
        assert done, outs[0][-3000:]
        assert int(done.group(1)) == 40
        assert "resumed from step" in outs[0], outs[0][-3000:]
        # the restored checkpoint really carried zero1-sharded state
        mdir = os.path.join(shared, "logs", "checkpoints", "manifests")
        manifests = [json.load(open(os.path.join(mdir, f)))
                     for f in os.listdir(mdir) if f.endswith(".json")]
        assert manifests and all(
            m["run"].get("grad_sync") == "zero1"
            for m in manifests), manifests

        # Fault-free reference on the shrunken mesh: the elastic run
        # resumed the same trajectory, so final losses must coincide.
        ref_shared = str(tmp_path / "ref")
        ref = subprocess.run(
            [sys.executable, "-m", "dtf_tpu.scenarios._host",
             spec.to_json(), "0", "1", ref_shared, "2", ""],
            cwd=tmp_path, env=child_env(4), stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, timeout=360)
        assert ref.returncode == 0, ref.stdout[-3000:]
        ref_done = re.search(r"SCENARIO_DONE steps=(\d+) "
                             r"final_cost=([0-9.]+)", ref.stdout)
        assert ref_done, ref.stdout[-3000:]
        assert abs(float(done.group(2))
                   - float(ref_done.group(2))) < 5e-3, \
            f"elastic zero1 loss {done.group(2)} != fault-free " \
            f"{ref_done.group(2)}"

    def test_two_process_restore_robust_fallback(self, tmp_path):
        """Multi-host restore_robust (tests/_mp_restore_robust.py): with
        the latest checkpoint corrupted on a shared directory, BOTH
        processes must agree on the coordinator's fallback pick and
        restore the same older step — a divergent local choice would
        deadlock the collective restore (this test would time out)."""
        port = free_port()
        driver = os.path.join(REPO_ROOT, "tests", "_mp_restore_robust.py")
        outs = run_workers(
            [[sys.executable, driver, str(task), str(port),
              str(tmp_path / "shared_ckpt")] for task in range(2)],
            n_local_devices=4, cwd=tmp_path)
        for task, out in enumerate(outs):
            assert "RESTORE_ROBUST_MP_OK step 10" in out, \
                f"task {task}:\n{out[-2000:]}"
