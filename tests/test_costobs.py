"""Device cost observatory (dtf_tpu/telemetry/costobs.py, ISSUE 15).

The honesty pins live here:

* **backend degradation** — ``cost_analysis()`` / ``memory_analysis()``
  returning None, raising, or reporting partial dicts must yield a
  well-formed CostCard with ``None`` fields, never a crash and never a
  fake zero a gate could pass on;
* **deterministic classification** — the CPU sim classifies against
  the pinned synthetic roofline entry, so compute-vs-memory verdicts
  are rig-independent;
* **explain ranking** — an A/B where one site's bytes grow must rank
  that site first, and the ``--max_hbm_frac`` / ``--max_compiles``
  gates are falsifiable (absence = FAIL, absurd threshold = FAIL).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import dtf_tpu.telemetry as tel
from dtf_tpu.telemetry import costobs
from dtf_tpu.telemetry.costobs import (CostCard, classify, diff_sites,
                                       read_costcards)
from dtf_tpu.utils.profiling import CPU_SIM_ROOFLINE, chip_roofline


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    tel.reset()
    yield
    tel.reset()


# ---------------------------------------------------------------------------
# fakes: every backend degradation shape in one place
# ---------------------------------------------------------------------------


class _Mem:
    def __init__(self, arg=None, out=None, temp=None, code=None,
                 alias=None):
        if arg is not None:
            self.argument_size_in_bytes = arg
        if out is not None:
            self.output_size_in_bytes = out
        if temp is not None:
            self.temp_size_in_bytes = temp
        if code is not None:
            self.generated_code_size_in_bytes = code
        if alias is not None:
            self.alias_size_in_bytes = alias


class _Compiled:
    def __init__(self, cost="raise", mem="raise"):
        self._cost = cost
        self._mem = mem

    def cost_analysis(self):
        if self._cost == "raise":
            raise NotImplementedError("backend reports nothing")
        return self._cost

    def memory_analysis(self):
        if self._mem == "raise":
            raise NotImplementedError("backend reports nothing")
        return self._mem


# ---------------------------------------------------------------------------
# capture honesty
# ---------------------------------------------------------------------------


class TestCaptureDegradation:
    def test_everything_raises_yields_null_card(self):
        card = costobs.observe("train/step", ("g",), _Compiled())
        assert card.flops is None and card.bytes_accessed is None
        assert card.peak_hbm_bytes is None
        assert card.flops_total is None and card.bytes_total is None
        assert card.bound == "unknown"
        assert card.n_compiles == 1

    def test_none_analysis(self):
        card = costobs.observe("train/step", ("g",),
                               _Compiled(cost=None, mem=None))
        assert card.flops is None and card.peak_hbm_bytes is None

    def test_partial_dict_keeps_missing_none(self):
        card = costobs.observe("train/step", ("g",),
                               _Compiled(cost={"flops": 10.0}, mem=None))
        assert card.flops == 10.0
        assert card.bytes_accessed is None      # absent, NOT zero
        assert card.bound == "unknown"          # can't classify w/o bytes

    def test_negative_sentinel_degrades_to_none(self):
        # XLA reports -1 for "unknown" — a gate must see absence
        card = costobs.observe(
            "train/step", ("g",),
            _Compiled(cost={"flops": -1.0, "bytes accessed": -1.0}))
        assert card.flops is None and card.bytes_accessed is None

    def test_list_of_dicts_form(self):
        # older jax returns [dict]; first computation wins
        card = costobs.observe(
            "train/step", ("g",),
            _Compiled(cost=[{"flops": 8.0, "bytes accessed": 2.0}]))
        assert card.flops == 8.0 and card.bytes_accessed == 2.0
        assert card.oi == 4.0

    def test_memory_fields_and_peak(self):
        card = costobs.observe(
            "train/step", ("g",),
            _Compiled(cost=None,
                      mem=_Mem(arg=100.0, out=50.0, temp=25.0, code=7.0,
                               alias=25.0)))
        assert card.argument_bytes == 100.0
        assert card.output_bytes == 50.0
        assert card.temp_bytes == 25.0
        assert card.generated_code_bytes == 7.0
        # arguments + outputs + temps - aliased
        assert card.peak_hbm_bytes == 150.0

    def test_doc_roundtrip_preserves_none(self):
        card = costobs.observe("serve/decode", (3, 8), _Compiled())
        back = CostCard.from_doc(json.loads(json.dumps(card.to_doc())))
        assert back.key() == card.key()
        assert back.flops is None and back.flops_total is None


class TestClassification:
    def test_cpu_roofline_is_pinned(self):
        rl = chip_roofline(jax.devices()[0])
        assert rl == CPU_SIM_ROOFLINE
        assert rl.synthetic
        assert rl.ridge_flops_per_byte == pytest.approx(2.0)

    def test_compute_vs_memory_vs_unknown(self):
        rl = CPU_SIM_ROOFLINE
        assert classify(40.0, 10.0, rl) == (4.0, "compute")
        assert classify(10.0, 10.0, rl) == (1.0, "memory")
        assert classify(None, 10.0, rl) == (None, "unknown")
        assert classify(10.0, None, rl) == (None, "unknown")
        assert classify(10.0, 5.0, None) == (2.0, "unknown")

    def test_real_compile_classifies_on_cpu_sim(self):
        # a real CPU-backend Compiled: analysis present, classification
        # deterministic against the pinned synthetic entry
        f = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((64, 64), jnp.float32)
        compiled = f.lower(a, a).compile()
        card = costobs.observe("bench/matmul", (64,), compiled)
        assert card.flops and card.bytes_accessed
        assert card.bound in ("compute", "memory")  # never unknown here
        assert card.peak_hbm_bytes and card.peak_hbm_bytes > 0


# ---------------------------------------------------------------------------
# observatory bookkeeping
# ---------------------------------------------------------------------------


class TestObservatory:
    def test_recompile_folds_into_card(self):
        obs = costobs.get_observatory()
        c = _Compiled(cost={"flops": 5.0, "bytes accessed": 10.0})
        costobs.observe("serve/decode", (3, 8), c)
        card = costobs.observe("serve/decode", (3, 8), c)
        assert card.n_compiles == 2
        assert card.flops_total == 10.0 and card.bytes_total == 20.0
        assert len(obs.cards()) == 1
        assert obs.total_compiles() == 2

    def test_instruments_book_as_group(self):
        costobs.observe("serve/decode", (3, 8),
                        _Compiled(cost={"flops": 5.0,
                                        "bytes accessed": 10.0}))
        snap = tel.get_registry().snapshot()
        assert snap["cost/compiles_total"]["value"] == 1
        assert snap["cost/cards"]["value"] == 1
        assert snap["cost/flops_total"]["value"] == 5.0
        assert snap["cost/bytes_total"]["value"] == 10.0

    def test_jsonl_roundtrip(self, tmp_path):
        costobs.observe("serve/decode", (3, 8),
                        _Compiled(cost={"flops": 5.0,
                                        "bytes accessed": 10.0}))
        costobs.observe("serve/prefill", (16,), _Compiled())
        path = costobs.get_observatory().write_jsonl(str(tmp_path))
        assert os.path.basename(path) == costobs.COSTCARDS_FILE
        cards = read_costcards(str(tmp_path))
        assert [c.site for c in cards] == ["serve/decode", "serve/prefill"]
        assert cards[1].flops is None

    def test_update_live_memory_sets_hbm_gauges(self):
        keep = jnp.ones((128, 128), jnp.float32)   # noqa: F841 (pinned live)
        live = costobs.get_observatory().update_live_memory()
        assert live and live >= keep.nbytes
        snap = tel.get_registry().snapshot()
        assert snap["hbm/live_bytes"]["value"] == live
        assert snap["hbm/live_bytes_peak"]["value"] >= live
        frac = snap["hbm/frac"]["value"]
        # denominator is the PROCESS capacity: chip capacity x local
        # devices (live_arrays sums every local device's shards)
        assert frac == pytest.approx(
            snap["hbm/live_bytes_peak"]["value"]
            / (CPU_SIM_ROOFLINE.hbm_capacity_bytes
               * len(jax.local_devices())))

    def test_memz_is_one_families_cut(self):
        costobs.observe("serve/decode", (3, 8),
                        _Compiled(cost={"flops": 5.0,
                                        "bytes accessed": 10.0}))
        tel.counter("serve/requests_completed").inc()   # outside families
        doc = costobs.get_observatory().memz()
        assert doc["cards"][0]["site"] == "serve/decode"
        assert "cost/compiles_total" in doc["metrics"]
        assert "serve/requests_completed" not in doc["metrics"]
        assert doc["summary"]["sites"]["serve/decode"]["compiles"] == 1

    def test_summary_is_deterministic(self):
        c = _Compiled(cost={"flops": 5.0, "bytes accessed": 10.0})
        costobs.observe("b", (1,), c)
        costobs.observe("a", (1,), c)
        s = costobs.get_observatory().summary()
        assert list(s["sites"]) == ["a", "b"]
        assert json.dumps(s, sort_keys=True)   # JSON-serializable


# ---------------------------------------------------------------------------
# the jit wrapper (the serving/bench compile sites run through this)
# ---------------------------------------------------------------------------


class TestInstrumentedJit:
    def test_captures_once_per_signature(self):
        jfn = jax.jit(lambda x: x * 2.0)
        inst = costobs.instrument(jfn, "bench/matmul", ("t",))
        x = jnp.arange(4.0)
        np.testing.assert_array_equal(np.asarray(inst(x)),
                                      np.asarray(jfn(x)))
        inst(x)                       # same signature: no new compile
        assert costobs.get_observatory().total_compiles() == 1
        inst(jnp.arange(8.0))         # new shape: one more compile
        card = costobs.get_observatory().cards()[0]
        assert card.n_compiles == 2
        assert card.site == "bench/matmul"
        # ping back to the first shape: the fast-path entry mismatches,
        # the slow path must hit the per-signature cache — NOT recompile
        inst(x)
        assert costobs.get_observatory().total_compiles() == 2

    def test_nested_geometry_roundtrips_hashable(self, tmp_path):
        """bench/breakdown geometries nest a shape tuple; JSON turns it
        into a list — from_doc must rebuild the SAME hashable key or
        explain's A/B pairing breaks (diff_cards indexes by key)."""
        from dtf_tpu.telemetry.costobs import diff_cards
        c = _Compiled(cost={"flops": 4.0, "bytes accessed": 2.0})
        costobs.observe("bench/breakdown", ("gelu", 2, (8, 8), "f32"), c)
        costobs.get_observatory().write_jsonl(str(tmp_path))
        back = read_costcards(str(tmp_path))
        assert back[0].key() == costobs.get_observatory().cards()[0].key()
        rows = diff_cards(back, back)      # must not raise unhashable
        # inner tuples stay tuples in-process (JSON listifies on write)
        assert rows[0]["geometry"] == ["gelu", 2, (8, 8), "f32"]

    def test_lowering_failure_falls_back_to_jit(self):
        calls = []

        class _Weird:
            def lower(self, *a):
                raise RuntimeError("lowering quirk")

            def __call__(self, x):
                calls.append(1)
                return x

        inst = costobs.instrument(_Weird(), "bench/matmul", ("t",))
        assert float(inst(jnp.float32(3.0))) == 3.0
        assert calls == [1]
        assert costobs.get_observatory().total_compiles() == 0

    def test_serve_builders_emit_cards_and_stay_token_identical(self):
        """The decode.py builders run through the wrapper: same tokens
        as ever (the wrapper executes the identical lowered program),
        one card per compiled geometry."""
        from dtf_tpu.models.gpt import GPT, GPTConfig
        from dtf_tpu.serve import ServingEngine, VirtualClock

        model = GPT(GPTConfig.tiny())
        params = model.init(jax.random.key(0))
        rng = np.random.default_rng(3)
        trace = [(0.02 * i, {"rid": i,
                             "prompt": rng.integers(0, 64, (5,))
                             .astype(np.int32),
                             "max_new_tokens": 4})
                 for i in range(3)]
        eng = ServingEngine(model, params, num_slots=3, block_size=4,
                            blocks_per_slot=8, clock=VirtualClock(),
                            seed=0)
        results = eng.run(list(trace))
        assert all(r.status == "completed" for r in results.values())
        cards = costobs.get_observatory().cards()
        sites = {c.site for c in cards}
        assert "serve/prefill" in sites or "serve/prefill_batched" in sites
        assert "serve/decode" in sites
        # one card per compiled geometry, every one actually compiled
        assert all(c.n_compiles >= 1 for c in cards)
        # KV gauges (satellite): registered from the engine iteration
        snap = tel.get_registry().snapshot()
        assert "serve/kv_blocks_in_use" in snap
        assert 0.0 <= snap["serve/kv_pool_frac"]["value"] <= 1.0
        assert snap["serve/kv_hot_prefix_blocks"]["value"] >= 1
        assert "hbm/kv_pool_bytes" in snap
        summ = eng.summary()
        assert summ["kv_blocks_in_use"] == 0          # all released
        assert summ["kv_pool_frac_peak"] > 0
        assert summ["kv_hot_prefix_blocks"] >= 1


# ---------------------------------------------------------------------------
# trainer AOT warmup capture
# ---------------------------------------------------------------------------


class _ProbeDataset:
    num_examples = 64

    def examples(self, lo, hi):
        rng = np.random.default_rng(0)
        n = hi - lo
        return (rng.random((n, 784)).astype(np.float32),
                np.eye(10, dtype=np.float32)[np.arange(n) % 10])


class TestTrainerAotCard:
    def test_aot_warmup_records_train_step_card(self, mesh8, tmp_path):
        from dtf_tpu import optim
        from dtf_tpu.cluster import Cluster, ClusterConfig
        from dtf_tpu.config import TrainConfig
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.train.trainer import Trainer

        cfg = TrainConfig(batch_size=64, learning_rate=0.05, epochs=1,
                          seed=1, logdir=str(tmp_path))
        trainer = Trainer(Cluster(config=ClusterConfig(), mesh=mesh8),
                          MnistMLP(init_scale="fan_in"), optim.sgd(0.05),
                          cfg)
        trainer._aot_warmup(_ProbeDataset(), 64)
        assert trainer._compiled_step is not None
        cards = [c for c in costobs.get_observatory().cards()
                 if c.site == "train/step"]
        assert len(cards) == 1
        assert cards[0].geometry == ("aot", 64)
        # the CPU backend reports analysis: real numbers, classified
        assert cards[0].flops and cards[0].flops > 0
        assert cards[0].bound in ("compute", "memory")


# ---------------------------------------------------------------------------
# telemetry.json + gates + /memz endpoint
# ---------------------------------------------------------------------------


class TestSyncPointAndGates:
    def _run_and_write(self, tmp_path):
        import time
        inst = costobs.instrument(jax.jit(lambda x: x @ x),
                                  "bench/matmul", (32,))
        # keep the result alive: hbm/live_bytes measures live_arrays()
        self._keep = inst(jnp.ones((32, 32), jnp.float32))
        # the implied --check wants goodput ~ wall: start the tracker
        # clock, then one measured block that IS ~all of the wall time
        tel.get_tracker().add("other", 0.0)
        with tel.get_tracker().measure("productive"):
            time.sleep(0.3)
        tel.write_telemetry_json(str(tmp_path))
        return str(tmp_path)

    def test_telemetry_json_carries_cost_section_and_cards(self, tmp_path):
        logdir = self._run_and_write(tmp_path)
        doc = json.load(open(os.path.join(logdir, "telemetry.json")))
        assert doc["cost"]["compiles"] == 1
        assert doc["cost"]["roofline"]["synthetic"] is True
        assert "bench/matmul" in doc["cost"]["sites"]
        assert os.path.exists(os.path.join(logdir,
                                           costobs.COSTCARDS_FILE))
        assert doc["metrics"]["hbm/frac"]["value"] > 0

    def test_gates_pass_sane_fail_absurd_fail_absent(self, tmp_path):
        from dtf_tpu.telemetry.report import build_report, check_gates
        logdir = self._run_and_write(tmp_path)
        report = build_report(logdir)
        ok, lines = check_gates(report, max_hbm_frac=0.9,
                                max_compiles=100)
        assert ok, lines
        ok, lines = check_gates(report, max_hbm_frac=1e-9)
        assert not ok
        ok, lines = check_gates(report, max_compiles=0)
        assert not ok
        # absence is a failure, not a pass
        os.makedirs(str(tmp_path / "nothing_here_"), exist_ok=True)
        empty = build_report(str(tmp_path / "nothing_here_"))
        ok, lines = check_gates(empty, max_hbm_frac=0.9)
        assert not ok and any("not measured" in ln for ln in lines)

    def test_report_cli_gate_exit_codes(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report as report_cli
        logdir = self._run_and_write(tmp_path)
        assert report_cli.main([logdir, "--max_hbm_frac", "0.9",
                                "--max_compiles", "100"]) == 0
        assert report_cli.main([logdir, "--max_hbm_frac",
                                "0.000000001"]) == 1
        capsys.readouterr()

    def test_memz_endpoint_serves_consistent_cut(self, tmp_path):
        import urllib.request

        from dtf_tpu.telemetry.live import AdminServer
        self._run_and_write(tmp_path)
        admin = AdminServer(0).start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{admin.port}/memz",
                    timeout=5) as r:
                doc = json.loads(r.read())
            assert doc["cards"][0]["site"] == "bench/matmul"
            assert "cost/compiles_total" in doc["metrics"]
            assert doc["summary"]["compiles"] == 1
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{admin.port}/", timeout=5) as r:
                root = json.loads(r.read())
            assert "/memz" in root["endpoints"]
        finally:
            admin.close()


# ---------------------------------------------------------------------------
# the explainer
# ---------------------------------------------------------------------------


def _write_run(tmp_path, name, cards, goodput=None):
    d = tmp_path / name
    d.mkdir()
    with open(d / costobs.COSTCARDS_FILE, "w") as f:
        for c in cards:
            f.write(json.dumps(c.to_doc(), sort_keys=True) + "\n")
    with open(d / "telemetry.json", "w") as f:
        json.dump({"goodput": goodput or {}, "metrics": {}}, f)
    return str(d)


def _card(site, geometry, bytes_t, flops_t, compiles=1):
    return CostCard(site=site, geometry=geometry,
                    bytes_total=bytes_t, flops_total=flops_t,
                    bytes_accessed=bytes_t, flops=flops_t,
                    n_compiles=compiles)


class TestExplain:
    def test_bytes_growth_ranks_first(self, tmp_path):
        a = _write_run(tmp_path, "a", [
            _card("serve/decode", (3, 4), 100.0, 100.0),
            _card("serve/prefill", (16,), 50.0, 60.0)],
            goodput={"productive_s": 1.0, "wall_s": 2.0})
        # B: decode context doubled — the wider bucket is a NEW geometry
        # whose bytes dominate the growth; prefill unchanged
        b = _write_run(tmp_path, "b", [
            _card("serve/decode", (3, 4), 100.0, 100.0),
            _card("serve/decode", (3, 8), 220.0, 105.0, compiles=2),
            _card("serve/prefill", (16,), 50.0, 60.0)],
            goodput={"productive_s": 2.0, "wall_s": 3.0})
        doc = costobs.explain(a, b)
        assert doc["ranked"][0]["site"] == "serve/decode"
        assert doc["ranked"][0]["verdict"] == "memory-bound growth"
        assert doc["ranked"][0]["compiles_b"] == 3
        # the flat site ranks below
        sites = [r["site"] for r in doc["ranked"]]
        assert sites.index("serve/decode") < sites.index("serve/prefill")
        # the new geometry shows as the top card, flagged NEW
        top_card = doc["cards"][0]
        assert top_card["site"] == "serve/decode"
        assert top_card["geometry"] == [3, 8] and not top_card["in_a"]
        lines = costobs.render_explain(doc)
        assert any("serve/decode" in ln and "memory-bound" in ln
                   for ln in lines)
        # phase deltas ride along
        assert doc["phases"]["productive_s"]["delta"] == pytest.approx(1.0)

    def test_site_rollup_verdicts(self):
        a = [_card("s", (1,), 100.0, 100.0)]
        flopsy = [_card("s", (1,), 102.0, 300.0)]
        assert diff_sites(a, flopsy)[0]["verdict"] == "compute-bound growth"
        flat = [_card("s", (1,), 101.0, 101.0)]
        assert diff_sites(a, flat)[0]["verdict"] == "flat"

    def test_compute_bound_regression_ranks_first(self):
        """Flat bytes + doubled flops must still outrank byte jitter —
        the ranking carries a flops term, not bytes alone."""
        a = [_card("decode", (1,), 100.0, 100.0),
             _card("prefill", (2,), 100.0, 100.0)]
        b = [_card("decode", (1,), 100.0, 300.0),     # flops tripled
             _card("prefill", (2,), 101.0, 100.0)]    # byte jitter
        ranked = diff_sites(a, b)
        assert ranked[0]["site"] == "decode"
        assert ranked[0]["verdict"] == "compute-bound growth"

    def test_json_doc_has_no_infinity(self, tmp_path):
        """A measured-zero base must not leak RFC-invalid Infinity into
        the --json document (zero-base ratios degrade to None)."""
        a = _write_run(tmp_path, "za", [_card("s", (1,), 0.0, 1.0)])
        b = _write_run(tmp_path, "zb", [_card("s", (1,), 50.0, 1.0)])
        doc = costobs.explain(a, b)
        text = json.dumps(doc)
        assert "Infinity" not in text
        assert doc["ranked"][0]["bytes_frac"] is None

    def test_missing_cards_is_loud(self, tmp_path):
        a = _write_run(tmp_path, "a", [_card("s", (1,), 1.0, 1.0)])
        empty = tmp_path / "b"
        empty.mkdir()
        with pytest.raises(FileNotFoundError):
            costobs.explain(a, str(empty))

    def test_explain_cli(self, tmp_path, capsys):
        from dtf_tpu.telemetry import report as report_cli
        a = _write_run(tmp_path, "a", [_card("serve/decode", (3, 4),
                                             100.0, 100.0)])
        b = _write_run(tmp_path, "b", [_card("serve/decode", (3, 4),
                                             300.0, 110.0)])
        assert report_cli.main(["--explain", a, b]) == 0
        out = capsys.readouterr().out
        assert "Ranked attribution" in out and "serve/decode" in out
        # missing cards -> exit 1 (absence loud)
        empty = tmp_path / "c"
        empty.mkdir()
        assert report_cli.main(["--explain", a, str(empty)]) == 1
        # a second logdir without --explain is a usage error
        assert report_cli.main([a, b]) == 2
        capsys.readouterr()


# ---------------------------------------------------------------------------
# ledger fold (satellite: optional columns, old rows untouched)
# ---------------------------------------------------------------------------


class TestLedgerCostColumns:
    def _mod(self):
        import importlib
        import sys
        scripts = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts")
        if scripts not in sys.path:
            sys.path.insert(0, scripts)
        return importlib.import_module("bench_ledger")

    def test_decode_row_folds_new_columns_only_when_present(self, tmp_path):
        bl = self._mod()
        new = {"tok_s_aggregate": 100.0, "rig": "decode_tiny_paged",
               "per_token_us": 10.0, "peak_hbm_bytes": 1.5e8,
               "n_compiles": 7}
        old = {"tok_s_aggregate": 100.0, "rig": "decode_tiny_paged",
               "per_token_us": 10.0}
        pn, po = tmp_path / "DECODE_r01.json", tmp_path / "DECODE_r02.json"
        json.dump(new, open(pn, "w"))
        json.dump(old, open(po, "w"))
        rn = bl.decode_row(str(pn), str(tmp_path))
        ro = bl.decode_row(str(po), str(tmp_path))
        assert rn["peak_hbm_bytes"] == 1.5e8 and rn["n_compiles"] == 7
        # pre-observatory docs fold WITHOUT the keys — committed
        # LEDGER.jsonl rows stay byte-stable
        assert "peak_hbm_bytes" not in ro and "n_compiles" not in ro

    def test_regression_names_the_quantity(self):
        bl = self._mod()

        def row(n, toks, hbm, compiles):
            return {"run": f"DECODE_r{n:02d}", "kind": "decode", "n": n,
                    "rig": "decode_tiny_paged", "ok": True, "error": None,
                    "tok_s_aggregate": toks, "peak_hbm_bytes": hbm,
                    "n_compiles": compiles}

        ok, lines = bl.check_ledger([row(1, 200.0, 1e8, 6),
                                     row(2, 100.0, 3e8, 18)])
        assert not ok
        named = [ln for ln in lines if "regressed quantity" in ln]
        assert named, lines
        assert "tok_s_aggregate" in named[0]
        assert "peak_hbm" in named[0] and "compiles" in named[0]

    def test_zero_valued_columns_still_diagnose(self):
        """A measured ZERO (0 compiles — everything cache-served) is
        exactly the reading whose jump is the diagnosis; truthiness
        must not drop it from the regressed-quantity line."""
        bl = self._mod()

        def row(n, toks, compiles):
            return {"run": f"DECODE_r{n:02d}", "kind": "decode", "n": n,
                    "rig": "r", "ok": True, "error": None,
                    "tok_s_aggregate": toks, "n_compiles": compiles}

        ok, lines = bl.check_ledger([row(1, 200.0, 0), row(2, 100.0, 40)])
        assert not ok
        named = [ln for ln in lines if "regressed quantity" in ln]
        assert named and "compiles 0 -> 40" in named[0], named

    def test_old_rows_without_columns_still_gate(self):
        bl = self._mod()
        rows = [{"run": "DECODE_r01", "kind": "decode", "n": 1,
                 "rig": "r", "ok": True, "error": None,
                 "tok_s_aggregate": 200.0},
                {"run": "DECODE_r02", "kind": "decode", "n": 2,
                 "rig": "r", "ok": True, "error": None,
                 "tok_s_aggregate": 100.0}]
        ok, lines = bl.check_ledger(rows)
        assert not ok
        named = [ln for ln in lines if "regressed quantity" in ln]
        assert named and "tok_s_aggregate" in named[0]
        assert "peak_hbm" not in named[0]      # columns absent: not faked
