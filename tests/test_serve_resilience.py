"""Overload-safe serving (ISSUE 10): deadline-aware shedding, the
brownout controller, priority + aging (starvation policy), cancel /
client-disconnect block release, graceful drain with token-identical
replay, serving chaos kinds, and the TCP front end.

The ISSUE-level pins:

* **shed before prefill** — a request that cannot meet its deadline
  under the current decode-rate estimate is dropped at the front door,
  booked under ``serve/shed_total`` with a reason, never prefilled;
* **no leaks** — after any churn of completions, cancels, drops, and
  evictions, ``allocator.free_count`` returns to its initial value;
* **drain loses zero accepted work** — a drained engine's replay docs,
  run through a fresh engine, produce token-identical results to an
  uninterrupted run (per-request rng streams are (seed, rid)-keyed);
* **starvation policy** — FIFO within a priority class, aging lifts
  waiters across classes, and the admission walk never skips past a
  block-starved request.
"""

import json
import socket
import threading

import numpy as np
import pytest

from dtf_tpu.serve import (BlockAllocator, BrownoutController, Request,
                           Scheduler, ServingEngine, VirtualClock)
from dtf_tpu.serve.brownout import LEVELS

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    from dtf_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig.tiny())
    return model, model.init(jax.random.key(0))


def _mk_engine(model, params, **kw):
    kw.setdefault("clock", VirtualClock())
    kw.setdefault("num_slots", 3)
    kw.setdefault("block_size", 4)
    kw.setdefault("blocks_per_slot", 8)
    return ServingEngine(model, params, **kw)


def _mk_trace(rng, n, *, qps=50.0, p_lens=(3, 5, 8), o_lens=(3, 6, 10),
              vocab=128, **extra):
    trace, t = [], 0.0
    for rid in range(n):
        t += float(rng.exponential(1.0)) / qps
        trace.append((t, {
            "rid": rid,
            "prompt": rng.integers(0, vocab,
                                   (int(rng.choice(p_lens)),)).astype(
                                       np.int32),
            "max_new_tokens": int(rng.choice(o_lens)),
            **extra,
        }))
    return trace


def _req(rid, p_len=4, max_new=4, t=0.0, **kw):
    return Request(rid=rid, prompt=np.zeros((p_len,), np.int32),
                   max_new_tokens=max_new, arrival_s=t, **kw)


def _sched(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("blocks_per_slot", 4)
    kw.setdefault("allocator",
                  BlockAllocator(1 + kw["num_slots"] * kw["blocks_per_slot"]))
    return Scheduler(**kw)


# ---------------------------------------------------------------------------
# deadline shedding (jax-free scheduler policy)
# ---------------------------------------------------------------------------


class TestDeadlineShedding:
    def test_hopeless_deadline_shed_at_submit(self):
        """The measured rate already rules this one out: shed at the
        front door, before it costs a queue entry."""
        s = _sched()
        s.decode_iter_s = 1.0               # 1s per token, measured
        sheds = []
        s.on_shed = lambda r, why: sheds.append((r.rid, why))
        r = _req(0, max_new=8, deadline_ms=500.0)
        assert s.submit(r, now=1.0) == "shed_deadline_unmeetable"
        assert r.status == "shed"
        assert r.shed_reason == "deadline_unmeetable"
        assert sheds == [(0, "deadline_unmeetable")]
        assert not s.queue                  # never cost a queue entry

    def test_deadline_expires_while_queued(self):
        """Feasible at submit, but the queue wait ate the budget: the
        admit walk sheds it with deadline_expired."""
        s = _sched()
        sheds = []
        s.on_shed = lambda r, why: sheds.append((r.rid, why))
        assert s.submit(_req(0, deadline_ms=50.0), now=0.0) == "queued"
        assert s.admit(now=1.0) == []       # 1s > 50ms deadline
        assert sheds == [(0, "deadline_expired")]
        assert not s.queue

    def test_unmeetable_deadline_shed_before_prefill(self):
        """The rate estimate says 8 remaining tokens need ~800ms; a
        500ms deadline is hopeless — shed at admit, BEFORE any prefill
        (the request never reaches the slot assignment)."""
        s = _sched()
        s.decode_iter_s = 0.1               # 100ms per token, measured
        sheds = []
        s.on_shed = lambda r, why: sheds.append(why)
        s.submit(_req(0, max_new=9, deadline_ms=500.0), now=0.0)
        got = s.admit(now=0.0)
        assert got == []
        assert sheds == ["deadline_unmeetable"]

    def test_cold_engine_never_sheds_on_estimates(self):
        """No observations yet -> estimator is 0 -> optimistic: the
        deadline check cannot fire on a fictitious rate."""
        s = _sched()
        assert s.submit(_req(0, max_new=8, deadline_ms=10.0),
                        now=0.0) == "queued"
        assert len(s.admit(0.0)) == 1

    def test_feasible_deadline_admits(self):
        s = _sched()
        s.decode_iter_s = 0.01
        s.prefill_s_per_token = 0.001
        s.submit(_req(0, max_new=4, deadline_ms=500.0), now=0.0)
        assert len(s.admit(0.0)) == 1

    def test_estimator_ewma_updates(self):
        s = _sched()
        s.observe_decode(0.1)
        assert s.decode_iter_s == pytest.approx(0.1)
        s.observe_decode(0.2)
        assert 0.1 < s.decode_iter_s < 0.2
        s.observe_prefill(10, 0.05)
        assert s.prefill_s_per_token == pytest.approx(0.005)


# ---------------------------------------------------------------------------
# priority + aging (the starvation policy, pinned)
# ---------------------------------------------------------------------------


class TestPriorityAndStarvation:
    def test_priority_order_fifo_within_class(self):
        s = _sched(num_slots=4, aging_s=0.0)
        s.submit(_req(0, priority=0), 0.0)
        s.submit(_req(1, priority=1), 0.1)
        s.submit(_req(2, priority=1), 0.2)
        s.submit(_req(3, priority=0), 0.3)
        got = [r.rid for _, r in s.admit(0.3)]
        # high class first, FIFO within each class
        assert got == [1, 2, 0, 3]

    def test_aging_lifts_a_low_priority_waiter(self):
        """SATELLITE PIN: a stream of high-priority shorts must not
        starve a low-priority request forever — after aging_s the
        waiter gains a level and admits ahead of fresher high-pri
        arrivals."""
        s = _sched(num_slots=1, aging_s=1.0)
        s.submit(_req(0, priority=0), 0.0)    # the would-be starved one
        s.submit(_req(1, priority=1), 0.1)
        got = s.admit(0.1)
        assert [r.rid for _, r in got] == [1]  # high pri wins while fresh
        s.release(got[0][1])
        # the stream keeps coming: each arrival is FRESH (effective
        # priority 1), while request 0's wait has lifted it to 0+2=2
        s.submit(_req(2, priority=1), 2.05)
        got2 = s.admit(2.1)
        assert [r.rid for _, r in got2] == [0]
        # and on an effective-priority TIE, earlier arrival wins (FIFO)
        s2 = _sched(num_slots=1, aging_s=1.0)
        s2.submit(_req(0, priority=0), 0.0)
        s2.submit(_req(1, priority=1), 1.15)   # fresh high: eff 1
        got3 = s2.admit(1.2)                   # waiter: eff 0+1=1, older
        assert [r.rid for _, r in got3] == [0]

    def test_no_skip_ahead_past_block_starved_head(self):
        """The other starvation half: when the head candidate cannot get
        blocks, later (smaller) candidates must NOT jump the line — the
        head keeps its claim on the next freed blocks."""
        s = _sched(num_slots=2, blocks_per_slot=4,
                   allocator=BlockAllocator(6))   # 5 usable blocks
        big = _req(0, p_len=14, max_new=2)        # needs 4 blocks
        small = _req(1, p_len=2, max_new=2)       # needs 1 block
        hog = _req(2, p_len=8, max_new=2)         # holds 2 blocks
        s.submit(hog, 0.0)
        assert len(s.admit(0.0)) == 1
        s.submit(big, 0.1)
        s.submit(small, 0.2)
        assert s.admit(0.2) == []                 # big can't fit: STOP
        s.release(hog)
        got = [r.rid for _, r in s.admit(0.3)]
        assert got[0] == 0                        # big goes first

    def test_effective_priority_math(self):
        s = _sched(aging_s=2.0)
        r = _req(0, priority=1, t=0.0)
        assert s.effective_priority(r, 1.9) == 1
        assert s.effective_priority(r, 2.1) == 2
        assert s.effective_priority(r, 6.5) == 4
        s2 = _sched(aging_s=0.0)
        assert s2.effective_priority(r, 100.0) == 1   # aging disabled


# ---------------------------------------------------------------------------
# cancel / release (the leak audit)
# ---------------------------------------------------------------------------


class TestCancelAndRelease:
    def test_cancel_queued_running_gone(self):
        s = _sched(num_slots=1)               # b stays queued behind a
        a, b = _req(0), _req(1)
        s.submit(a, 0.0)
        s.submit(b, 0.0)
        (slot, ra), = [x for x in s.admit(0.0) if x[1] is a]
        free0 = s.allocator.free_count
        assert s.cancel(b) == "queued"
        assert b.status == "cancelled" and not s.queue
        assert s.cancel(a) == "running"
        assert s.allocator.free_count > free0
        assert s.cancel(a) == "gone"              # idempotent
        assert s.allocator.free_count == s.allocator.num_blocks - 1

    def test_release_is_idempotent_not_double_free(self):
        s = _sched()
        s.submit(_req(0), 0.0)
        (slot, r), = s.admit(0.0)
        s.release(r)
        s.release(r)                              # no ValueError
        assert s.allocator.free_count == s.allocator.num_blocks - 1


# ---------------------------------------------------------------------------
# brownout controller (jax-free)
# ---------------------------------------------------------------------------


class TestBrownoutController:
    def test_escalates_with_dwell_hysteresis(self):
        c = BrownoutController(100.0, dwell_iters=3)
        for i in range(20):
            c.observe_ttft(500.0)
            c.update(i)
        assert c.level == 3                       # reached reject_all
        # transitions respected the dwell: gaps >= 3 iterations
        its = [t[0] for t in c.transitions]
        assert all(b - a >= 3 for a, b in zip(its, its[1:]))
        assert [t[1:] for t in c.transitions] == [(0, 1), (1, 2), (2, 3)]

    def test_deescalates_when_signal_recovers(self):
        c = BrownoutController(100.0, dwell_iters=2, exit_ratio=0.5)
        for i in range(10):
            c.observe_ttft(500.0)
            c.update(i)
        assert c.level == 3
        for i in range(10, 60):
            c.observe_ttft(10.0)                  # fast again
            c.update(i)
        assert c.level == 0
        assert LEVELS[c.level] == "normal"

    def test_idle_decay_unlatches_reject_all(self):
        """At reject_all nothing completes, so TTFT observations stop —
        the stale signal must decay or the brownout latches forever."""
        c = BrownoutController(100.0, dwell_iters=2)
        for i in range(10):
            c.observe_ttft(1000.0)
            c.update(i)
        assert c.level == 3
        for i in range(10, 200):                  # silence: no obs, no queue
            c.update(i)
        assert c.level == 0

    def test_queue_wait_is_an_early_warning(self):
        """No completions at all (hard wedge): the head-of-queue wait
        alone must escalate the controller."""
        c = BrownoutController(100.0, dwell_iters=1)
        for i in range(10):
            c.update(i, queue_head_wait_s=1.0)    # 1000ms >> 100ms SLO
        assert c.level >= 1

    def test_levels_gate_admissions(self):
        c = BrownoutController(100.0, degrade_max_new=4,
                               low_priority_max=0)
        assert c.max_new_cap() is None and c.submit_verdict(0) is None
        c.level = 1
        assert c.max_new_cap() == 4 and c.submit_verdict(0) is None
        c.level = 2
        assert c.submit_verdict(0) == "brownout_low_priority"
        assert c.submit_verdict(1) is None
        c.level = 3
        assert c.submit_verdict(1) == "brownout_admissions"

    def test_rejects_bad_hysteresis(self):
        with pytest.raises(ValueError, match="hysteresis"):
            BrownoutController(100.0, enter_ratio=0.5, exit_ratio=0.7)
        with pytest.raises(ValueError, match="slo"):
            BrownoutController(0.0)


# ---------------------------------------------------------------------------
# engine: shed booking, churn leak audit, chaos kinds
# ---------------------------------------------------------------------------


class TestEngineOverload:
    def test_sheds_booked_with_reasons(self, tiny_model):
        import dtf_tpu.telemetry as tel
        model, params = tiny_model
        tel.reset()
        eng = _mk_engine(model, params)
        eng.scheduler.decode_iter_s = 1.0     # measured-slow engine
        r = eng.submit(np.arange(4), 8, deadline_ms=500.0)
        assert r.status == "shed"
        s = eng.summary()
        assert s["shed"] == 1
        assert s["shed_reasons"] == {"deadline_unmeetable": 1}
        assert tel.get_registry().counter("serve/shed_total").value == 1
        assert tel.get_registry().counter(
            "serve/shed_deadline_unmeetable").value == 1

    def test_churn_with_random_cancels_leaks_nothing(self, tiny_model):
        """SATELLITE PIN: allocator.free_count returns to initial after
        a churn run where a third of the requests are cancelled at
        random iterations (queued, mid-prefill reservation, and
        mid-decode alike)."""
        model, params = tiny_model
        eng = _mk_engine(model, params, num_blocks=1 + 3 * 8)
        free0 = eng.scheduler.allocator.free_count
        rng = np.random.default_rng(41)
        trace = _mk_trace(rng, 12, qps=60.0)
        cancel_at = {int(r): int(rng.integers(1, 10))
                     for r in rng.choice(12, size=4, replace=False)}
        i = 0
        while i < len(trace) or eng.scheduler.has_work():
            now = eng.clock.now()
            while i < len(trace) and trace[i][0] <= now:
                eng.submit(arrival_s=trace[i][0], **trace[i][1])
                i += 1
            for rid, it in list(cancel_at.items()):
                if eng.iterations >= it:
                    eng.cancel(rid)
                    del cancel_at[rid]
            if eng.scheduler.has_work():
                eng.step()
            elif i < len(trace):
                eng.clock.advance_to(trace[i][0])
        assert eng.scheduler.allocator.free_count == free0
        s = eng.summary()
        assert s["cancelled"] >= 1
        assert s["completed"] + s["cancelled"] == 12

    def test_client_drop_chaos_frees_blocks(self, tiny_model):
        from dtf_tpu.resilience.chaos import FaultPlan
        model, params = tiny_model
        plan = FaultPlan.parse("client_drop@3", process_index=0)
        eng = _mk_engine(model, params, chaos=plan, num_slots=2)
        rng = np.random.default_rng(7)
        res = eng.run([(0.0, dict(rid=i,
                                  prompt=rng.integers(0, 128, (5,))
                                  .astype(np.int32),
                                  max_new_tokens=12))
                       for i in range(2)])
        statuses = sorted(r.status for r in res.values())
        assert statuses == ["cancelled", "completed"]
        assert res[0].status == "cancelled"       # oldest active dropped
        assert eng.scheduler.allocator.free_count == \
            eng.scheduler.allocator.num_blocks - 1

    def test_kv_poison_evicts_only_the_victim(self, tiny_model):
        """HBM corruption of one request's blocks: the decode step's
        finite-logits flag must catch it, the engine evicts exactly the
        victim (status failed, blocks freed), and every other request
        completes with untouched tokens."""
        import jax.numpy as jnp
        from dtf_tpu.resilience.chaos import FaultPlan
        model, params = tiny_model
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 128, (5,)).astype(np.int32)
                   for _ in range(3)]
        # reference tokens for the survivors
        refs = [np.asarray(model.generate(
            params, jnp.asarray(p)[None], 10,
            temperature=0.0))[0, 5:].tolist() for p in prompts]
        plan = FaultPlan.parse("kv_poison@4", process_index=0)
        eng = _mk_engine(model, params, chaos=plan)
        res = eng.run([(0.0, dict(rid=i, prompt=p, max_new_tokens=10))
                       for i, p in enumerate(prompts)])
        assert res[0].status == "failed"          # the oldest = victim
        for i in (1, 2):
            assert res[i].status == "completed"
            assert res[i].tokens == refs[i], f"survivor {i} corrupted"
        assert eng.scheduler.allocator.free_count == \
            eng.scheduler.allocator.num_blocks - 1
        # the poisoned blocks were SCRUBBED before returning to the
        # free list: a post-poison churn that recycles every block
        # (lowest-id-first reuses the victim's) must complete cleanly —
        # unscrubbed NaN rows would evict innocent requests forever
        res2 = eng.run([(0.0, dict(rid=10 + i, prompt=p,
                                   max_new_tokens=10))
                        for i, p in enumerate(prompts * 2)])
        assert all(r.status == "completed" for r in res2.values()
                   if r.rid >= 10), {r.rid: r.status
                                     for r in res2.values()}
        assert res2[10].tokens == refs[0]         # victim's prompt, clean

    def test_slow_decode_chaos_inflates_measured_latency(self, tiny_model):
        from dtf_tpu.resilience.chaos import FaultPlan
        model, params = tiny_model
        rng = np.random.default_rng(13)
        trace = _mk_trace(rng, 4, qps=100.0)

        def run(chaos):
            plan = (FaultPlan.parse(chaos, process_index=0)
                    if chaos else None)
            eng = _mk_engine(model, params, chaos=plan)
            eng.run([(t, dict(kw)) for t, kw in trace])
            return eng.summary(slo_ttft_ms=1e9)

        base = run(None)
        slow = run("slow_decode@2:100ms")
        assert slow["ttft_ms_p99"] > base["ttft_ms_p99"] + 50.0
        assert slow["completed"] == base["completed"] == 4


# ---------------------------------------------------------------------------
# brownout end-to-end: the overload A/B gates (in-process)
# ---------------------------------------------------------------------------


class TestOverloadGates:
    def test_chaos_ab_controller_wins_under_spike(self, tiny_model):
        """The acceptance gates, in-process on the virtual clock: zero
        deadline violations in the controller arm, sheds booked with
        reasons, controller strictly improves goodput-QPS on the same
        trace under the same persistent decode-rate spike."""
        import argparse
        from dtf_tpu.bench.serve_load import chaos_ab
        model, params = tiny_model
        ns = argparse.Namespace(
            clock="virtual", seed=0, slots=4, block_size=16,
            pool_blocks=None, max_queue=256, top_k=0, top_p=1.0,
            temperature=0.0, requests=60, qps_list=[10.0],
            prompt_lens_list=[4, 8, 16], output_lens_list=[2, 8, 16],
            slo_ttft_ms=400.0, deadline_ms=2500.0,
            priorities_list=[0, 0, 1], degrade_max_new=8,
            chaos="slow_decode@30:60ms")
        out = chaos_ab(model, params, ns)
        assert out["ok"], out["gates"]
        on, off = out["controller"], out["no_controller"]
        assert on["deadline_violations"] == 0
        assert on["shed"] > 0 and on["shed_reasons"]
        assert on["goodput_qps"] > off["goodput_qps"]
        # the brownout actually engaged and is observable
        assert on["brownout"]["transitions"] >= 1

    def test_degrade_level_clamps_max_new(self, tiny_model):
        model, params = tiny_model
        bo = BrownoutController(100.0, degrade_max_new=3)
        bo.level = 1
        eng = _mk_engine(model, params, brownout=bo)
        r = eng.submit(np.arange(4), 20)
        assert r.max_new_tokens == 3 and r.degraded
        eng.run([])
        assert eng.summary()["degraded"] == 1


# ---------------------------------------------------------------------------
# graceful drain + replay
# ---------------------------------------------------------------------------


class TestDrain:
    def test_drain_finishes_inflight_and_checkpoints_queue(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params, num_slots=2)
        rng = np.random.default_rng(17)
        trace = _mk_trace(rng, 6, qps=200.0)
        real_step = eng.step

        def step():
            if eng.iterations == 3:
                eng.request_drain()
            return real_step()

        eng.step = step
        eng.run(trace)
        assert eng.drained
        s = eng.summary()
        # nothing accepted was lost: every request is completed or in
        # the drain docs (none merely vanished)
        drained_rids = {d["rid"] for d in eng.drain_docs}
        completed = {rid for rid, r in eng.results.items()
                     if r.status == "completed"}
        accepted = completed | drained_rids
        assert s["drained_unfinished"] == len(drained_rids) > 0
        assert all(r.status in ("completed", "drained")
                   for r in eng.results.values())
        assert accepted == set(range(len(eng.results)))
        # blocks all came home
        assert eng.scheduler.allocator.free_count == \
            eng.scheduler.allocator.num_blocks - 1

    def test_drain_replay_is_token_identical(self, tiny_model):
        """ACCEPTANCE PIN: replaying a drain's checkpointed requests in
        a fresh engine yields the SAME tokens an uninterrupted run
        produces — the PR 7 determinism guarantee extended across
        preemption."""
        model, params = tiny_model
        rng = np.random.default_rng(19)
        trace = _mk_trace(rng, 6, qps=150.0, temperature=1.0)

        ref_eng = _mk_engine(model, params, seed=5)
        refs = ref_eng.run([(0.0, dict(kw)) for _, kw in trace])

        eng = _mk_engine(model, params, seed=5)
        real_step = eng.step

        def step():
            if eng.iterations == 4:
                eng.request_drain()
            return real_step()

        eng.step = step
        eng.run(trace)
        assert eng.drain_docs, "nothing was drained — no preemption?"
        replay_eng = _mk_engine(model, params, seed=5)
        replayed = replay_eng.run(
            [(0.0, {**d, "prompt": np.asarray(d["prompt"], np.int32)})
             for d in eng.drain_docs])
        for doc in eng.drain_docs:
            rid = doc["rid"]
            assert replayed[rid].tokens == refs[rid].tokens, rid

    def test_submit_rejected_while_draining(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params)
        eng.scheduler.draining = True
        r = eng.submit(np.arange(4), 4)
        assert r.status == "rejected"

    def test_drain_timeout_checkpoints_inflight(self, tiny_model):
        model, params = tiny_model
        eng = _mk_engine(model, params)
        eng.submit(np.arange(6), 24)    # in-window, can't finish in 0s
        eng.step()                      # prefill + first decode
        out = eng.drain(timeout_s=0.0)
        assert out["timed_out"]
        assert [d["rid"] for d in out["unfinished"]] == [0]
        assert eng.scheduler.allocator.free_count == \
            eng.scheduler.allocator.num_blocks - 1


# ---------------------------------------------------------------------------
# serving gates in report.check_gates (jax-free)
# ---------------------------------------------------------------------------


class TestServingGates:
    def _report(self, **serving):
        return {"telemetry": {"serving": serving}}

    def test_serving_gates_pass_and_fail(self):
        from dtf_tpu.telemetry.report import check_gates
        rep = self._report(goodput_qps=5.0, ttft_ms_p99=300.0)
        ok, lines = check_gates(rep, min_goodput_qps=2.0,
                                max_ttft_p99_ms=400.0)
        assert ok and len(lines) == 2
        ok, lines = check_gates(rep, min_goodput_qps=9.0)
        assert not ok
        ok, lines = check_gates(rep, max_ttft_p99_ms=100.0)
        assert not ok

    def test_missing_serving_section_fails_armed_gates(self):
        from dtf_tpu.telemetry.report import check_gates
        ok, lines = check_gates({}, min_goodput_qps=1.0)
        assert not ok and "not measured" in lines[0]

    def test_serve_spec_validation(self):
        from dtf_tpu.scenarios.spec import Gate, ScenarioSpec
        with pytest.raises(ValueError, match="goodput-QPS floor"):
            ScenarioSpec(name="s", workload="serve",
                         gate=Gate(max_final_cost=None, min_goodput=0.1))
        with pytest.raises(ValueError, match="no loss curve"):
            ScenarioSpec(name="s", workload="serve",
                         gate=Gate(max_final_cost=1.0, min_goodput=0.1,
                                   min_goodput_qps=1.0))
        with pytest.raises(ValueError, match="convergence target"):
            ScenarioSpec(name="t", workload="mnist",
                         gate=Gate(max_final_cost=None, min_goodput=0.1))
        # the real serve cell round-trips through JSON like any other
        spec = ScenarioSpec(
            name="ok", workload="serve",
            chaos="slow_decode@10:50ms",
            gate=Gate(max_final_cost=None, min_goodput=0.05,
                      min_goodput_qps=1.0, max_ttft_p99_ms=900.0))
        assert ScenarioSpec.from_json(spec.to_json()) == spec
        assert "min_goodput_qps" in spec.gate.thresholds()


# ---------------------------------------------------------------------------
# TCP front end — protocol units (fast) + socket end-to-end (slow)
# ---------------------------------------------------------------------------


class TestFrontendProtocol:
    def test_parse_listen(self):
        from dtf_tpu.serve.frontend import parse_listen
        assert parse_listen(":8100") == ("127.0.0.1", 8100)
        assert parse_listen("0.0.0.0:9") == ("0.0.0.0", 9)
        for bad in ("8100", "host:", "host:abc"):
            with pytest.raises(ValueError):
                parse_listen(bad)

    def test_parse_request_line_valid(self):
        from dtf_tpu.serve.frontend import parse_request_line
        kw = parse_request_line(json.dumps(
            {"prompt": [1, 2, 3], "max_new_tokens": 4,
             "deadline_ms": 100, "priority": 1,
             "temperature": 0.5}).encode())
        assert kw["max_new_tokens"] == 4 and kw["priority"] == 1
        assert kw["deadline_ms"] == 100
        np.testing.assert_array_equal(kw["prompt"], [1, 2, 3])

    @pytest.mark.parametrize("line", [
        b"not json at all",
        b'"just a string"',
        b'{"max_new_tokens": 4}',                      # no prompt
        b'{"prompt": []}',                             # empty prompt
        b'{"prompt": ["a", "b"]}',                     # non-int tokens
        b'{"prompt": [1], "max_new_tokens": 0}',
        b'{"prompt": [1], "deadline_ms": -5}',
        b'{"prompt": [1], "priority": "high"}',
        b'{"prompt": [1], "temperature": -1}',
    ])
    def test_parse_request_line_rejects_garbage(self, line):
        from dtf_tpu.serve.frontend import parse_request_line
        with pytest.raises(ValueError):
            parse_request_line(line)


def _client(addr, lines, read_until_done=True, keep_open=False):
    """Tiny line-protocol client: send request lines, collect response
    docs until the terminal status line."""
    out = []
    sock = socket.create_connection(addr, timeout=30.0)
    try:
        f = sock.makefile("rwb")
        for line in lines:
            f.write(line.encode() + b"\n")
            f.flush()
            while read_until_done:
                resp = f.readline()
                if not resp:
                    return out
                doc = json.loads(resp)
                out.append(doc)
                if "error" in doc or "status" in doc:
                    break
    finally:
        if not keep_open:
            sock.close()
    return out


@pytest.mark.slow
class TestTCPFrontend:
    """Socket end-to-end (slow marker: stays out of the tier-1 budget;
    the full-suite serve-chaos lane runs these via `pytest -m "serve
    and slow"`)."""

    def _serve(self, model, params, drain_timeout_s=30.0, **kw):
        from dtf_tpu.serve import WallClock
        from dtf_tpu.serve.frontend import TCPFrontend
        # wide window (the tiny preset's max_len 64) so the long-stream
        # tests can keep a request in flight while the client misbehaves
        kw.setdefault("blocks_per_slot", 16)
        eng = _mk_engine(model, params, clock=WallClock(), **kw)
        fe = TCPFrontend(eng, "127.0.0.1", 0, conn_timeout_s=5.0)
        thread = threading.Thread(
            target=fe.run_loop, kwargs={"drain_timeout_s": drain_timeout_s},
            daemon=True)
        thread.start()
        return eng, fe, thread

    def test_request_streams_reference_tokens(self, tiny_model):
        import jax.numpy as jnp
        model, params = tiny_model
        rng = np.random.default_rng(23)
        prompt = rng.integers(0, 128, (5,)).astype(np.int32)
        ref = np.asarray(model.generate(
            params, jnp.asarray(prompt)[None], 6,
            temperature=0.0))[0, 5:].tolist()
        eng, fe, thread = self._serve(model, params)
        try:
            docs = _client(fe.address, [json.dumps(
                {"prompt": prompt.tolist(), "max_new_tokens": 6})])
            tokens = [d["token"] for d in docs if "token" in d]
            assert tokens == ref
            assert docs[-1]["status"] == "completed"
            assert docs[-1]["n_tokens"] == 6
        finally:
            fe.shutdown()
            thread.join(timeout=10)

    def test_malformed_request_gets_error_line(self, tiny_model):
        model, params = tiny_model
        eng, fe, thread = self._serve(model, params)
        try:
            docs = _client(fe.address, ['{"prompt": "garbage"}'])
            assert "error" in docs[0]
            # the server survives: a good request still works
            docs2 = _client(fe.address, [json.dumps(
                {"prompt": [1, 2], "max_new_tokens": 2})])
            assert docs2[-1]["status"] == "completed"
        finally:
            fe.shutdown()
            thread.join(timeout=10)

    def test_disconnect_mid_stream_frees_blocks(self, tiny_model):
        import time as _time
        from dtf_tpu.resilience.chaos import FaultPlan
        model, params = tiny_model
        # slow the engine (50ms/iteration via the chaos hook — the wall
        # clock really sleeps) so the 56-token stream is still in
        # flight when the client vanishes
        eng, fe, thread = self._serve(
            model, params,
            chaos=FaultPlan.parse("slow_decode@1:50ms", process_index=0))
        free0 = eng.scheduler.allocator.num_blocks - 1
        try:
            sock = socket.create_connection(fe.address, timeout=10.0)
            f = sock.makefile("rwb")
            f.write((json.dumps({"prompt": [3, 1, 4],
                                 "max_new_tokens": 56}) + "\n").encode())
            f.flush()
            first = json.loads(f.readline())
            assert "token" in first
            sock.close()                  # vanish mid-stream
            deadline = _time.monotonic() + 20.0
            while _time.monotonic() < deadline:
                if (eng.scheduler.allocator.free_count == free0
                        and eng.scheduler.num_active() == 0):
                    break
                _time.sleep(0.05)
            assert eng.scheduler.allocator.free_count == free0, \
                "disconnect leaked KV blocks"
            # the bridge's per-request stream map must not leak either
            # (cancel emits a terminal event that pops the entry)
            assert not fe.bridge._streams, \
                "stream map leaked after disconnect"
        finally:
            fe.shutdown()
            thread.join(timeout=10)

    def test_sigterm_drain_tells_waiting_clients(self, tiny_model):
        from dtf_tpu.resilience.chaos import FaultPlan
        model, params = tiny_model
        eng, fe, thread = self._serve(
            model, params, drain_timeout_s=0.5,
            chaos=FaultPlan.parse("slow_decode@1:50ms", process_index=0))
        try:
            sock = socket.create_connection(fe.address, timeout=10.0)
            f = sock.makefile("rwb")
            f.write((json.dumps({"prompt": [3, 1, 4],
                                 "max_new_tokens": 56}) + "\n").encode())
            f.flush()
            assert "token" in json.loads(f.readline())
            eng.request_drain()           # what SIGTERM does
            docs = []
            while True:
                line = f.readline()
                if not line:
                    break
                doc = json.loads(line)
                docs.append(doc)
                if "status" in doc:
                    break
            # ~2.8s of stream cannot finish inside the 0.5s grace: the
            # engine checkpoints it and the client hears "drained"
            assert docs and docs[-1].get("status") == "drained"
            thread.join(timeout=30)
            assert not thread.is_alive()
            assert [d["rid"] for d in eng.drain_docs] == [0]
            sock.close()
        finally:
            fe.shutdown()


# ---------------------------------------------------------------------------
# serve CLI: --drain_at + supervisor replay (slow, like TestServeCLI)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestDrainCLI:
    def test_drain_at_with_restart_budget_replays_everything(
            self, tmp_path, capsys):
        from dtf_tpu.serve.__main__ import main
        tokens_a = tmp_path / "drained.json"
        rc = main(["--preset", "tiny", "--demo", "6", "--qps", "50",
                   "--clock", "virtual", "--seed", "3",
                   "--drain_at", "3", "--max_restarts", "1",
                   "--logdir", str(tmp_path / "run"),
                   "--tokens_out", str(tokens_a)])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["completed_all_attempts"] == 6
        # the supervisor replay completed everything, so the drain
        # hand-off file must be GONE — a stale drain.jsonl would tell
        # the operator to re-serve requests that already completed
        drain_file = tmp_path / "run" / "drain.jsonl"
        assert not drain_file.exists()
        # ACCEPTANCE: token-identical to an uninterrupted run
        tokens_b = tmp_path / "clean.json"
        rc = main(["--preset", "tiny", "--demo", "6", "--qps", "50",
                   "--clock", "virtual", "--seed", "3",
                   "--tokens_out", str(tokens_b)])
        assert rc == 0
        capsys.readouterr()
        assert json.loads(tokens_a.read_text()) == \
            json.loads(tokens_b.read_text())

    def test_drain_at_without_budget_exits_clean_with_handoff(
            self, tmp_path, capsys):
        """--max_restarts 0: the drain file is the hand-off; the exit
        is clean (nothing accepted was LOST — it is checkpointed)."""
        from dtf_tpu.serve.__main__ import main
        rc = main(["--preset", "tiny", "--demo", "6", "--qps", "500",
                   "--clock", "virtual", "--seed", "3",
                   "--drain_at", "3", "--max_restarts", "0",
                   "--logdir", str(tmp_path / "run")])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["drained_unfinished"] > 0
        drain_file = tmp_path / "run" / "drain.jsonl"
        docs = [json.loads(x) for x in
                drain_file.read_text().splitlines()]
        assert len(docs) == summary["drained_unfinished"]
        # the hand-off replays through --requests and completes
        rc = main(["--preset", "tiny", "--requests", str(drain_file),
                   "--clock", "virtual", "--seed", "3"])
        assert rc == 0
        replay = json.loads(capsys.readouterr().out)
        assert replay["completed"] == len(docs)
