"""Flash-attention kernel vs naive attention: forward + all gradients,
causal and full, multi-block grids, bf16 inputs.  Runs in pallas interpret
mode on the CPU test rig (the kernel auto-detects non-TPU backends)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.nn.attention import MultiHeadAttention, dot_product_attention
from dtf_tpu.ops.flash_attention import flash_attention, flash_attention_impl


def naive(q, k, v, causal=False, kv_mask=None):
    """Reference attention in (B, H, T, D) layout, fp32."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, jnp.finfo(jnp.float32).min)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s,
                      jnp.finfo(jnp.float32).min)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))


def rand_qkv(key, shape, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    mk = lambda k: jax.random.normal(k, shape, dtype)
    return mk(kq), mk(kk), mk(kv)


class TestForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_naive_multiblock(self, causal):
        # T=64 with block 16 -> 4x4 block grid exercises the online softmax
        q, k, v = rand_qkv(jax.random.key(0), (2, 3, 64, 32))
        out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        np.testing.assert_allclose(out, naive(q, k, v, causal), atol=2e-5)

    def test_single_block(self):
        q, k, v = rand_qkv(jax.random.key(1), (1, 2, 16, 8))
        out = flash_attention(q, k, v)
        np.testing.assert_allclose(out, naive(q, k, v), atol=2e-5)

    def test_uneven_blocks(self):
        # block_q != block_k
        q, k, v = rand_qkv(jax.random.key(2), (1, 1, 64, 16))
        out = flash_attention(q, k, v, block_q=32, block_k=16)
        np.testing.assert_allclose(out, naive(q, k, v), atol=2e-5)

    def test_bf16_inputs(self):
        q, k, v = rand_qkv(jax.random.key(3), (1, 2, 32, 16), jnp.bfloat16)
        out = flash_attention(q, k, v, block_q=16, block_k=16)
        ref = naive(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32))
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=2e-2)

    def test_indivisible_seq_adapts_block(self):
        """Block sizes shrink to the largest divisor of T (T=48 with 32
        requested -> 24), so off-size sequences still work."""
        q, k, v = rand_qkv(jax.random.key(4), (1, 1, 48, 8))
        out = flash_attention(q, k, v, block_q=32, block_k=32)
        np.testing.assert_allclose(out, naive(q, k, v), atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_kv_mask_multiblock(self, causal):
        """Per-key padding mask across a 4x4 block grid, including one row
        whose ENTIRE FIRST k block is padded (exercises the finite
        MASK_VALUE self-correction) and a padded tail block."""
        q, k, v = rand_qkv(jax.random.key(11), (3, 2, 64, 16))
        valid = jnp.stack([
            jnp.arange(64) < 40,                    # padded tail block
            jnp.arange(64) >= 16,                   # first block all-masked
            jnp.ones(64, bool),                     # no padding
        ])
        out = flash_attention(q, k, v, causal=causal, kv_mask=valid,
                              block_q=16, block_k=16)
        ref = naive(q, k, v, causal, kv_mask=valid)
        if causal:
            # rows 0..15 of batch 1 see no keys at all under causal+mask;
            # their output is undefined by contract — compare the rest
            out = out[:, :, 16:]
            ref = ref[:, :, 16:]
        np.testing.assert_allclose(out, ref, atol=2e-5)


class TestBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_naive(self, causal):
        q, k, v = rand_qkv(jax.random.key(5), (2, 2, 64, 16))

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=16, block_k=16) ** 2)

        def f_naive(q, k, v):
            return jnp.sum(naive(q, k, v, causal) ** 2)

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_naive = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
        for gf, gn, name in zip(g_flash, g_naive, "qkv"):
            np.testing.assert_allclose(gf, gn, atol=5e-5,
                                       err_msg=f"d{name} mismatch")

    def test_grads_match_naive_with_kv_mask(self):
        q, k, v = rand_qkv(jax.random.key(12), (2, 2, 64, 16))
        valid = jnp.stack([jnp.arange(64) < 48, jnp.arange(64) >= 16])

        def f_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, kv_mask=valid,
                                           block_q=16, block_k=16) ** 2)

        def f_naive(q, k, v):
            return jnp.sum(naive(q, k, v, kv_mask=valid) ** 2)

        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_naive = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
        for gf, gn, name in zip(g_flash, g_naive, "qkv"):
            np.testing.assert_allclose(gf, gn, atol=5e-5,
                                       err_msg=f"d{name} mismatch")

    def test_grads_under_jit_and_vmap_composition(self):
        # the kernel must trace inside jit (the train step is one program)
        q, k, v = rand_qkv(jax.random.key(6), (1, 2, 32, 8))

        @jax.jit
        def loss(q, k, v):
            return jnp.mean(flash_attention(q, k, v, block_q=16, block_k=16))

        g = jax.grad(loss)(q, k, v)
        assert g.shape == q.shape
        assert bool(jnp.all(jnp.isfinite(g)))


class TestMHAIntegration:
    def test_attn_impl_plugs_into_mha(self):
        mha = MultiHeadAttention(dim=32, num_heads=4,
                                 attn_impl=flash_attention_impl(block_q=16,
                                                                block_k=16))
        mha_ref = MultiHeadAttention(dim=32, num_heads=4)
        params = mha.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2, 32, 32))
        np.testing.assert_allclose(mha.apply(params, x),
                                   mha_ref.apply(params, x), atol=2e-5)

    def test_key_padding_mask_runs_on_kernel(self):
        """BERT's pad_mask[:, None, None, :] form routes to the Pallas
        kernel and matches the XLA path."""
        q, k, v = rand_qkv(jax.random.key(8), (2, 32, 4, 8))  # (B,T,H,D)
        pad = jnp.arange(32)[None, :] < jnp.asarray([32, 20])[:, None]
        mask4 = pad[:, None, None, :]
        impl = flash_attention_impl(block_q=16, block_k=16)
        np.testing.assert_allclose(impl(q, k, v, mask4),
                                   dot_product_attention(q, k, v, mask4),
                                   atol=2e-5)

    def test_general_mask_falls_back_to_xla(self):
        """A per-query mask can't use the kernel's per-key bias: the
        adapter must still produce correct output via the XLA path."""
        q, k, v = rand_qkv(jax.random.key(9), (1, 16, 2, 8))
        mask = jax.random.bernoulli(jax.random.key(10), 0.7,
                                    (1, 1, 16, 16))
        mask = mask.at[:, :, :, 0].set(True)       # keep rows non-empty
        impl = flash_attention_impl()
        np.testing.assert_allclose(impl(q, k, v, mask),
                                   dot_product_attention(q, k, v, mask),
                                   atol=2e-5)

    def test_layout_adapter_matches_dot_product_attention(self):
        key = jax.random.key(7)
        q, k, v = rand_qkv(key, (2, 16, 4, 8))     # (B, T, H, D) layout
        impl = flash_attention_impl(block_q=16, block_k=16)
        np.testing.assert_allclose(impl(q, k, v),
                                   dot_product_attention(q, k, v), atol=2e-5)


class TestValidation:
    def test_cross_attention_rejected(self):
        """The kernel grid tiles one sequence length: Tq != Tk must raise
        a descriptive error, not an opaque kernel failure (ADVICE r2)."""
        q, _, _ = rand_qkv(jax.random.key(0), (1, 16, 2, 8))
        k, _, _ = rand_qkv(jax.random.key(1), (1, 32, 2, 8))
        v = k
        with pytest.raises(ValueError, match="self-attention only"):
            flash_attention(q.transpose(0, 2, 1, 3),
                            k.transpose(0, 2, 1, 3),
                            v.transpose(0, 2, 1, 3))

    def test_kv_mask_wrong_length_rejected(self):
        q, k, v = rand_qkv(jax.random.key(2), (1, 2, 16, 8))  # (B,H,T,D)
        bad = jnp.ones((1, 8), bool)
        with pytest.raises(ValueError, match="key .*length|Tk"):
            flash_attention(q, k, v, kv_mask=bad)
