"""Pipeline parallelism: GPipe schedule over a 'pipe' mesh axis must equal
sequentially applying the stages; gradients flow through the backward
pipeline; composes with the data axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.parallel.mesh import make_mesh
from dtf_tpu.parallel.pipeline import pipeline_apply


@pytest.fixture()
def pipe_mesh():
    """4-stage pipeline x 2-way data on the 8-device rig."""
    return make_mesh("data=2,pipe=4")


@pytest.fixture()
def pipe_data_mesh():
    return make_mesh("data=4,pipe=2")


def mlp_stage(params, x):
    """One pipeline stage: dense + gelu (shape-preserving)."""
    return jax.nn.gelu(x @ params["w"] + params["b"])


def make_stages(key, s, d):
    kw, = jax.random.split(key, 1)
    ws = jax.random.normal(kw, (s, d, d)) / np.sqrt(d)
    return {"w": ws, "b": jnp.zeros((s, d))}


def sequential(params, x):
    for i in range(params["w"].shape[0]):
        x = mlp_stage(jax.tree_util.tree_map(lambda p: p[i], params), x)
    return x


class TestPipeline:
    @pytest.mark.parametrize("m", [1, 4, 8])
    def test_matches_sequential(self, pipe_mesh, m):
        params = make_stages(jax.random.key(0), 4, 16)
        x = jax.random.normal(jax.random.key(1), (16, 16))
        y = pipeline_apply(mlp_stage, params, x, pipe_mesh,
                           num_microbatches=m)
        np.testing.assert_allclose(y, sequential(params, x), atol=1e-5)

    def test_composes_with_data_axis(self, pipe_data_mesh):
        params = make_stages(jax.random.key(2), 2, 8)
        x = jax.random.normal(jax.random.key(3), (16, 8))
        y = pipeline_apply(mlp_stage, params, x, pipe_data_mesh,
                           num_microbatches=2)
        np.testing.assert_allclose(y, sequential(params, x), atol=1e-5)

    def test_under_jit(self, pipe_mesh):
        params = make_stages(jax.random.key(4), 4, 8)
        x = jax.random.normal(jax.random.key(5), (8, 8))

        @jax.jit
        def f(params, x):
            return pipeline_apply(mlp_stage, params, x, pipe_mesh,
                                  num_microbatches=4)

        np.testing.assert_allclose(f(params, x), sequential(params, x),
                                   atol=1e-5)

    def test_backward_pipeline_grads(self, pipe_mesh):
        params = make_stages(jax.random.key(6), 4, 8)
        x = jax.random.normal(jax.random.key(7), (8, 8))

        def loss_pipe(params):
            y = pipeline_apply(mlp_stage, params, x, pipe_mesh,
                               num_microbatches=4)
            return jnp.sum(y ** 2)

        def loss_seq(params):
            return jnp.sum(sequential(params, x) ** 2)

        gp = jax.grad(loss_pipe)(params)
        gs = jax.grad(loss_seq)(params)
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_rank3_activations(self, pipe_mesh):
        """Transformer-shaped activations (B, T, D)."""
        params = make_stages(jax.random.key(8), 4, 8)
        x = jax.random.normal(jax.random.key(9), (4, 6, 8))
        y = pipeline_apply(mlp_stage, params, x, pipe_mesh,
                           num_microbatches=2)
        np.testing.assert_allclose(y, sequential(params, x), atol=1e-5)

    def test_validation_errors(self, pipe_mesh):
        params = make_stages(jax.random.key(0), 4, 8)
        x = jnp.zeros((8, 8))
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(mlp_stage, params, x, pipe_mesh,
                           num_microbatches=3)
        with pytest.raises(ValueError, match="no 'pipe' axis"):
            pipeline_apply(mlp_stage, params, x, make_mesh("data=8"),
                           num_microbatches=2)
        bad = make_stages(jax.random.key(0), 3, 8)   # 3 stages on pipe=4
        with pytest.raises(ValueError, match="stage_params leading dim"):
            pipeline_apply(mlp_stage, bad, x, pipe_mesh, num_microbatches=2)
