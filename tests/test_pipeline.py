"""Pipeline parallelism: the GPipe schedule over a 'pipe' mesh axis must
equal sequentially applying the stages (forward + AD backward, ctx and aux
plumbing); the 1F1B schedule must produce the same loss and gradients as
the unpipelined reference while stashing only O(S) activations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.parallel.mesh import make_mesh
from dtf_tpu.parallel.pipeline import (bubble_fraction, pipeline_apply,
                                       pipeline_train_1f1b)


@pytest.fixture()
def pipe_mesh():
    """4-stage pipeline x 2-way data on the 8-device rig."""
    return make_mesh("data=2,pipe=4")


@pytest.fixture()
def pipe_data_mesh():
    return make_mesh("data=4,pipe=2")


def mlp_stage(params, x, ctx=None):
    """One pipeline stage: dense + gelu (shape-preserving).  Aux = mean of
    the pre-activation (a differentiable stand-in for a router loss)."""
    h = x @ params["w"] + params["b"]
    return jax.nn.gelu(h), jnp.mean(h.astype(jnp.float32))


def make_stages(key, s, d):
    kw, = jax.random.split(key, 1)
    ws = jax.random.normal(kw, (s, d, d)) / np.sqrt(d)
    return {"w": ws, "b": jnp.zeros((s, d))}


def sequential(params, x):
    aux = 0.0
    for i in range(params["w"].shape[0]):
        x, a = mlp_stage(jax.tree_util.tree_map(lambda p: p[i], params), x)
        aux = aux + a
    return x, aux


class TestPipeline:
    @pytest.mark.parametrize("m", [1, 4, 8])
    def test_matches_sequential(self, pipe_mesh, m):
        params = make_stages(jax.random.key(0), 4, 16)
        x = jax.random.normal(jax.random.key(1), (16, 16))
        y, aux = pipeline_apply(mlp_stage, params, x, pipe_mesh,
                                num_microbatches=m)
        y_ref, _ = sequential(params, x)
        np.testing.assert_allclose(y, y_ref, atol=1e-5)

    def test_aux_sums_over_stages_and_microbatches(self, pipe_mesh):
        params = make_stages(jax.random.key(0), 4, 16)
        x = jax.random.normal(jax.random.key(1), (16, 16))
        m = 4
        _, aux = pipeline_apply(mlp_stage, params, x, pipe_mesh,
                                num_microbatches=m)
        # reference: per-microbatch means summed over stages and mbs
        xs = x.reshape(m, 4, 16)
        want = sum(float(sequential(params, xs[k])[1]) for k in range(m))
        assert float(aux) == pytest.approx(want, abs=1e-4)

    def test_ctx_routes_per_microbatch(self, pipe_mesh):
        """A per-example ctx (e.g. a padding mask) must reach every stage
        aligned with its microbatch."""
        params = make_stages(jax.random.key(2), 4, 8)
        x = jax.random.normal(jax.random.key(3), (8, 8))
        gate = (jnp.arange(8) % 2 == 0).astype(jnp.float32)[:, None]

        def gated_stage(p, h, ctx):
            y, aux = mlp_stage(p, h * ctx["gate"])
            return y, aux

        y, _ = pipeline_apply(gated_stage, params, x, pipe_mesh,
                              num_microbatches=2, ctx={"gate": gate})
        ref = x
        for i in range(4):
            ref, _ = mlp_stage(
                jax.tree_util.tree_map(lambda p: p[i], params), ref * gate)
        np.testing.assert_allclose(y, ref, atol=1e-5)

    def test_composes_with_data_axis(self, pipe_data_mesh):
        params = make_stages(jax.random.key(2), 2, 8)
        x = jax.random.normal(jax.random.key(3), (16, 8))
        y, _ = pipeline_apply(mlp_stage, params, x, pipe_data_mesh,
                              num_microbatches=2)
        np.testing.assert_allclose(y, sequential(params, x)[0], atol=1e-5)

    def test_under_jit(self, pipe_mesh):
        params = make_stages(jax.random.key(4), 4, 8)
        x = jax.random.normal(jax.random.key(5), (8, 8))

        @jax.jit
        def f(params, x):
            return pipeline_apply(mlp_stage, params, x, pipe_mesh,
                                  num_microbatches=4)[0]

        np.testing.assert_allclose(f(params, x), sequential(params, x)[0],
                                   atol=1e-5)

    def test_backward_pipeline_grads(self, pipe_mesh):
        params = make_stages(jax.random.key(6), 4, 8)
        x = jax.random.normal(jax.random.key(7), (8, 8))

        def loss_pipe(params):
            y, aux = pipeline_apply(mlp_stage, params, x, pipe_mesh,
                                    num_microbatches=4)
            return jnp.sum(y ** 2) + 0.1 * aux

        def loss_seq(params):
            y, aux = sequential(params, x)
            # pipeline aux is summed over per-microbatch means: with 4 mbs
            # of 2 rows each, that equals 4x the per-mb mean... recompute
            xs = x.reshape(4, 2, 8)
            aux_p = sum(sequential(params, xs[k])[1] for k in range(4))
            return jnp.sum(y ** 2) + 0.1 * aux_p

        gp = jax.grad(loss_pipe)(params)
        gs = jax.grad(loss_seq)(params)
        for a, b in zip(jax.tree_util.tree_leaves(gp),
                        jax.tree_util.tree_leaves(gs)):
            np.testing.assert_allclose(a, b, atol=1e-5)

    def test_rank3_activations(self, pipe_mesh):
        """Transformer-shaped activations (B, T, D)."""
        params = make_stages(jax.random.key(8), 4, 8)
        x = jax.random.normal(jax.random.key(9), (4, 6, 8))
        y, _ = pipeline_apply(mlp_stage, params, x, pipe_mesh,
                              num_microbatches=2)
        np.testing.assert_allclose(y, sequential(params, x)[0], atol=1e-5)

    def test_validation_errors(self, pipe_mesh):
        params = make_stages(jax.random.key(0), 4, 8)
        x = jnp.zeros((8, 8))
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(mlp_stage, params, x, pipe_mesh,
                           num_microbatches=3)
        with pytest.raises(ValueError, match="no 'pipe' axis"):
            pipeline_apply(mlp_stage, params, x, make_mesh("data=8"),
                           num_microbatches=2)
        bad = make_stages(jax.random.key(0), 3, 8)   # 3 stages on pipe=4
        with pytest.raises(ValueError, match="stage_params leading dim"):
            pipeline_apply(mlp_stage, bad, x, pipe_mesh, num_microbatches=2)


class Test1F1B:
    """pipeline_train_1f1b vs the unpipelined reference: loss, stage
    grads, head grads, and the input cotangent must all match."""

    def _head_loss(self, hp, y_mb, ctx_mb):
        logits = y_mb @ hp["w_out"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tl = jnp.take_along_axis(logp, ctx_mb["labels"][:, None],
                                 axis=-1)[:, 0]
        return -jnp.mean(tl)

    def _reference(self, params, head, x, labels, m, aux_weight):
        """Unpipelined: mean over microbatches of (loss + w*aux)."""
        xs = x.reshape(m, x.shape[0] // m, -1)
        ls = labels.reshape(m, -1)

        def total(params, head, x):
            out = jnp.float32(0)
            for k in range(m):
                y, aux = sequential(params, xs_dyn(x, k))
                l = self._head_loss(head, y, {"labels": ls[k]})
                out = out + l / m + aux_weight * aux / m
            return out

        def xs_dyn(x, k):
            return x.reshape(m, x.shape[0] // m, -1)[k]

        val, grads = jax.value_and_grad(total, argnums=(0, 1, 2))(
            params, head, x)
        return val, grads

    @pytest.mark.parametrize("m,aux_w", [(4, 0.0), (8, 0.05)])
    def test_matches_unpipelined_grads(self, pipe_mesh, m, aux_w):
        d, b = 8, 16
        params = make_stages(jax.random.key(10), 4, d)
        head = {"w_out": jax.random.normal(jax.random.key(11), (d, 12))}
        x = jax.random.normal(jax.random.key(12), (b, d))
        labels = jax.random.randint(jax.random.key(13), (b,), 0, 12)

        loss, sg, hg, dx = pipeline_train_1f1b(
            mlp_stage, self._head_loss, params, head, x,
            {"labels": labels}, pipe_mesh, num_microbatches=m,
            aux_weight=aux_w)

        ref_total, (g_ref, h_ref, dx_ref) = self._reference(
            params, head, x, labels, m, aux_w)
        # reference total includes the aux term; 1F1B reports the pure
        # loss mean, so compare loss without aux
        xs = x.reshape(m, b // m, d)
        pure = np.mean([float(self._head_loss(
            head, sequential(params, xs[k])[0],
            {"labels": labels.reshape(m, -1)[k]})) for k in range(m)])
        assert float(loss) == pytest.approx(pure, abs=1e-5)
        for a, r in zip(jax.tree_util.tree_leaves(sg),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(a, r, atol=1e-4)
        for a, r in zip(jax.tree_util.tree_leaves(hg),
                        jax.tree_util.tree_leaves(h_ref)):
            np.testing.assert_allclose(a, r, atol=1e-4)
        np.testing.assert_allclose(dx, dx_ref, atol=1e-4)

    def test_data_axis_composition(self, pipe_data_mesh):
        d, b, m = 8, 16, 4
        params = make_stages(jax.random.key(14), 2, d)
        head = {"w_out": jax.random.normal(jax.random.key(15), (d, 6))}
        x = jax.random.normal(jax.random.key(16), (b, d))
        labels = jax.random.randint(jax.random.key(17), (b,), 0, 6)

        loss, sg, hg, dx = pipeline_train_1f1b(
            mlp_stage, self._head_loss, params, head, x,
            {"labels": labels}, pipe_data_mesh, num_microbatches=m)
        _, (g_ref, h_ref, dx_ref) = self._reference(params, head, x,
                                                    labels, m, 0.0)
        for a, r in zip(jax.tree_util.tree_leaves(sg),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(a, r, atol=1e-4)
        np.testing.assert_allclose(dx, dx_ref, atol=1e-4)

    def test_bubble_fraction_shrinks_with_m(self):
        assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
        assert bubble_fraction(4, 16) == pytest.approx(3 / 19)
        assert bubble_fraction(4, 64) < 0.05


class TestBert1F1B:
    """BertMLM's 1F1B path end to end: custom_grads_fn grads must match
    jax.grad of the equivalent GPipe-path loss, and train a step through
    the Trainer seam."""

    def test_grads_match_gpipe_path(self):
        from dtf_tpu.models.bert import BertConfig, BertMLM

        mesh = make_mesh("data=4,pipe=2")
        kw = dict(mlm_predictions=4, pipeline_mesh=mesh,
                  pipeline_microbatches=4)
        m_1f1b = BertMLM(BertConfig.tiny(pipeline_schedule="1f1b", **kw))
        m_gpipe = BertMLM(BertConfig.tiny(**kw))
        params = m_gpipe.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (16, 32), 4, 128)
        rng = jax.random.key(2)

        loss1, metrics, g1 = m_1f1b.pipeline_loss_and_grads(
            params, {"tokens": toks}, rng)

        def gpipe_loss(p):
            return m_gpipe.loss(p, {"tokens": toks}, rng=rng)[0]

        loss2, g2 = jax.value_and_grad(gpipe_loss)(params)
        assert float(loss1) == pytest.approx(float(loss2), abs=2e-5)
        flat1 = jax.tree_util.tree_leaves_with_path(g1)
        flat2 = dict(jax.tree_util.tree_leaves_with_path(g2))
        for path, leaf in flat1:
            np.testing.assert_allclose(
                leaf, flat2[path], atol=3e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_grad_accum_composes_with_grads_fn(self):
        """grad_accum atop the 1F1B schedule: the trainer accumulates
        per-microbatch grads OUTSIDE the schedule; must equal the mean of
        the schedule's grads over the strided microbatch split (rng folded
        per microbatch, same as the value_and_grad path)."""
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)
        from dtf_tpu import optim

        mesh = make_mesh("data=4,pipe=2")
        kw = dict(mlm_predictions=4, pipeline_mesh=mesh,
                  pipeline_microbatches=2, pipeline_schedule="1f1b")
        m = BertMLM(BertConfig.tiny(**kw))
        params = m.init(jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (16, 32), 4, 128)
        rng = jax.random.key(2)

        # manual accumulation: strided halves, fold_in(rng, i)
        micro = np.moveaxis(
            np.asarray(toks).reshape(8, 2, 32), 1, 0)
        losses, grads = [], []
        for i in range(2):
            li, _, gi = m.pipeline_loss_and_grads(
                params, {"tokens": jnp.asarray(micro[i])},
                jax.random.fold_in(rng, i))
            losses.append(float(li))
            grads.append(gi)
        want = jax.tree_util.tree_map(lambda a, b: (a + b) / 2, *grads)

        # trainer path with grad_accum=2: inspect via a sgd(1.0) step
        # (params' change == -grads)
        opt = optim.sgd(1.0)
        state = init_state(m, opt, seed=0, mesh=mesh)
        state["params"] = params
        step = make_train_step(m.loss, opt, mesh, grad_accum=2,
                               grads_fn=m.pipeline_loss_and_grads,
                               donate=False)
        new_state, metrics = step(state, put_global_batch(mesh, {"tokens": toks}),
                                  rng)
        assert float(metrics["loss"]) == pytest.approx(
            (losses[0] + losses[1]) / 2, abs=1e-5)
        got = jax.tree_util.tree_map(lambda a, b: a - b,
                                     state["params"], new_state["params"])
        flat_w = dict(jax.tree_util.tree_leaves_with_path(want))
        for path, leaf in jax.tree_util.tree_leaves_with_path(got):
            np.testing.assert_allclose(
                np.asarray(leaf, np.float32), flat_w[path], atol=3e-4,
                err_msg=jax.tree_util.keystr(path))

    def test_activation_memory_flat_in_microbatches(self):
        """The point of 1F1B: compiled temp (activation) memory stays O(S)
        as M grows, while GPipe-by-AD stores all M microbatch activations
        (measured on this rig: ~5x less at M=8, flat 13.5 -> 13.8 MB from
        M=8 -> 16 while GPipe holds ~70 MB)."""
        import jax.numpy as jnp

        from dtf_tpu.models.bert import BertConfig, BertMLM

        mesh = make_mesh("data=2,pipe=4")
        toks = jnp.zeros((32, 128), jnp.int32)
        rng = jax.random.key(0)

        def temp_bytes(schedule, m):
            kw = dict(vocab_size=512, dim=128, num_layers=4, num_heads=4,
                      mlp_dim=512, max_len=128, mask_token=3,
                      mlm_predictions=16, pipeline_mesh=mesh,
                      pipeline_microbatches=m,
                      pipeline_schedule=schedule)
            model = BertMLM(BertConfig(**kw))
            params = model.init(jax.random.key(1))
            if schedule == "1f1b":
                fn = lambda p: model.pipeline_loss_and_grads(
                    p, {"tokens": toks}, rng)[2]
            else:
                fn = jax.grad(
                    lambda p: model.loss(p, {"tokens": toks}, rng=rng)[0])
            c = jax.jit(fn).lower(params).compile()
            return c.memory_analysis().temp_size_in_bytes

        gp8 = temp_bytes("gpipe", 8)
        f8 = temp_bytes("1f1b", 8)
        f16 = temp_bytes("1f1b", 16)
        assert f8 < gp8 / 2, (gp8, f8)
        assert f16 < f8 * 1.5, (f8, f16)     # O(S), not O(M)

    def test_trains_through_trainer_step(self):
        from dtf_tpu import optim
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        mesh = make_mesh("data=4,pipe=2")   # tiny has 2 layers -> 2 stages
        cfg = BertConfig.tiny(mlm_predictions=4, pipeline_mesh=mesh,
                              pipeline_microbatches=4,
                              pipeline_schedule="1f1b")
        model = BertMLM(cfg)
        opt = optim.adam(1e-3)
        state = init_state(model, opt, seed=0, mesh=mesh)
        step = make_train_step(model.loss, opt, mesh, donate=False,
                               grads_fn=model.custom_grads_fn)
        losses = []
        for i in range(8):
            toks = jax.random.randint(jax.random.key(i), (16, 32), 4, 128)
            batch = put_global_batch(mesh, {"tokens": toks})
            state, m = step(state, batch, jax.random.key(100 + i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0]
