"""ResNet-50/CIFAR tests: shapes, BN state threading, sharded DP training.

BASELINE.md config row "ResNet-50 / CIFAR-10 sync all-reduce"; the reference
has no conv model, so numerics anchors are closed-form (BN statistics) and
convergence on the synthetic CIFAR task.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu import optim
from dtf_tpu.models.resnet import ResNet, ResNetConfig, max_pool
from dtf_tpu.parallel import sharding as sh
from dtf_tpu.train.trainer import init_state, make_train_step, put_global_batch


@pytest.fixture(scope="module")
def tiny():
    return ResNet(ResNetConfig.tiny())


class TestResNetModel:
    def test_forward_shape_and_state(self, tiny):
        params = tiny.init(jax.random.key(0))
        state = tiny.init_model_state()
        x = jnp.ones((4, 32, 32, 3))
        logits, new_state = tiny.apply_stateful(params, state, x, train=True)
        assert logits.shape == (4, 10)
        assert logits.dtype == jnp.float32
        # training updated every BN running stat away from init
        leaves_old = jax.tree_util.tree_leaves(state)
        leaves_new = jax.tree_util.tree_leaves(new_state)
        changed = [not np.allclose(a, b)
                   for a, b in zip(leaves_old, leaves_new)]
        assert all(changed), "some BN stats did not update in train mode"

    def test_eval_does_not_touch_state(self, tiny):
        params = tiny.init(jax.random.key(0))
        state = tiny.init_model_state()
        _, new_state = tiny.apply_stateful(params, state,
                                           jnp.ones((2, 32, 32, 3)),
                                           train=False)
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(new_state)):
            np.testing.assert_array_equal(a, b)

    def test_scan_matches_unrolled(self):
        """Scanned rest-blocks must equal applying the block sequentially."""
        cfg = ResNetConfig.tiny(stage_sizes=(3,), widths=(8,))
        m = ResNet(cfg)
        params = m.init(jax.random.key(1))
        state = m.init_model_state()
        x = jax.random.normal(jax.random.key(2), (2, 32, 32, 3))
        y_scan, _ = m.apply_stateful(params, state, x, train=False)

        # manual unroll: stem, first, then each rest block by index
        first, rest, n_rest = m.stages[0]
        h = m.stem.apply(params["stem"], x)
        h, _ = m.stem_bn.apply_stateful(params["stem_bn"], state["stem_bn"],
                                        h, train=False)
        h = jax.nn.relu(h)
        h, _ = first.apply_stateful(params["s0_first"], state["s0_first"], h,
                                    train=False)
        for k in range(n_rest):
            p_k = jax.tree_util.tree_map(lambda a: a[k], params["s0_rest"])
            s_k = jax.tree_util.tree_map(lambda a: a[k], state["s0_rest"])
            h, _ = rest.apply_stateful(p_k, s_k, h, train=False)
        h = jnp.mean(h, axis=(1, 2))
        y_manual = m.fc.apply(params["fc"], h).astype(jnp.float32)
        np.testing.assert_allclose(y_scan, y_manual, atol=1e-5)

    def test_resnet50_param_count(self):
        """ImageNet ResNet-50 has ~25.6M params; ours (no BN moving to
        params, conv-only, 10 classes, cifar stem) should land near 23.5M."""
        m = ResNet(ResNetConfig.resnet50())
        params = m.init(jax.random.key(0))
        n = sum(x.size for x in jax.tree_util.tree_leaves(params))
        assert 22e6 < n < 26e6, f"unexpected param count {n}"

    def test_imagenet_stem_downsamples(self):
        m = ResNet(ResNetConfig.tiny(cifar_stem=False))
        params = m.init(jax.random.key(0))
        state = m.init_model_state()
        logits, _ = m.apply_stateful(params, state, jnp.ones((1, 64, 64, 3)),
                                     train=False)
        assert logits.shape == (1, 10)

    def test_max_pool(self):
        x = jnp.arange(16.0).reshape(1, 4, 4, 1)
        y = max_pool(x, 2, 2)
        np.testing.assert_array_equal(y[0, :, :, 0],
                                      [[5.0, 7.0], [13.0, 15.0]])


class TestResNetTraining:
    def test_dp_train_step_runs_and_learns(self, tiny, mesh8):
        opt = optim.momentum(0.05)
        state = init_state(tiny, opt, seed=0, mesh=mesh8)
        assert "model_state" in state
        step = make_train_step(tiny.loss, opt, mesh8, stateful=True,
                               donate=False)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
        labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
        batch = put_global_batch(mesh8, (x, labels))
        losses = []
        for i in range(5):
            state, metrics = step(state, batch, jax.random.key(i))
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0], f"no learning: {losses}"
        assert int(state["step"]) == 5
        # BN running stats moved from init
        stem_mean = state["model_state"]["stem_bn"]["mean"]
        assert not np.allclose(np.asarray(stem_mean), 0.0)

    def test_explicit_mode_close_to_implicit(self, tiny, mesh8):
        """Implicit = synchronized BN (GSPMD global batch stats); explicit =
        local per-shard BN (classic non-sync DP semantics).  They are
        different estimators of the same statistics, so one step agrees
        approximately, not bitwise (documented in make_train_step)."""
        opt = optim.sgd(0.1)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
        labels = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
        out = {}
        for mode in ("implicit", "explicit"):
            state = init_state(tiny, opt, seed=0, mesh=mesh8)
            step = make_train_step(tiny.loss, opt, mesh8, mode=mode,
                                   stateful=True, donate=False)
            batch = put_global_batch(mesh8, (x, labels))
            state, metrics = step(state, batch, jax.random.key(0))
            out[mode] = (jax.device_get(state["model_state"]),
                         float(metrics["loss"]))
        assert abs(out["implicit"][1] - out["explicit"][1]) < 0.15
        # pmean of local means == global mean, so the running *mean* stats
        # agree tightly (running var differs by the between-shard variance).
        np.testing.assert_allclose(
            out["implicit"][0]["stem_bn"]["mean"],
            out["explicit"][0]["stem_bn"]["mean"], atol=1e-5)

    def test_axes_cover_params(self, tiny, mesh8):
        params = tiny.init(jax.random.key(0))
        shardings = sh.apply_rules(tiny.axes(), mesh8)
        # same treedef -> every param leaf has a sharding
        assert (jax.tree_util.tree_structure(params)
                == jax.tree_util.tree_structure(shardings))


class TestCifarWorkload:
    def test_cli_runs_one_epoch(self, tmp_path, monkeypatch, capsys):
        from dtf_tpu.workloads.cifar import main
        monkeypatch.chdir(tmp_path)   # no real CIFAR -> synthetic
        rc = main(["--epochs", "1", "--batch_size", "256", "--arch", "tiny",
                   "--logdir", str(tmp_path / "logs"),
                   "--log_frequency", "20"])
        assert rc == 0
        outp = capsys.readouterr().out
        assert "Test-Accuracy" in outp
        assert "done" in outp
