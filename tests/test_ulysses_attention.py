"""Ulysses (all-to-all) sequence parallelism vs full attention on the
8-device CPU mesh: exactness, causal masking, gradients through the
all-to-alls, the flash-kernel inner path, head-divisibility bound, and
composition with data parallelism and BERT."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.nn.attention import MultiHeadAttention, dot_product_attention
from dtf_tpu.ops.ulysses_attention import (ulysses_attention,
                                           ulysses_attention_impl)
from dtf_tpu.parallel.mesh import make_mesh


@pytest.fixture()
def seq_mesh():
    return make_mesh("seq=8")


@pytest.fixture()
def data_seq_mesh():
    return make_mesh("data=2,seq=4")


def rand_qkv(key, shape, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, shape, dtype) for k in (kq, kk, kv))


def naive_causal(q, k, v):
    t = q.shape[1]
    mask = jnp.tril(jnp.ones((t, t), bool))[None, None]
    return dot_product_attention(q, k, v, mask=mask)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, seq_mesh, causal):
        q, k, v = rand_qkv(jax.random.key(0), (2, 64, 8, 16))
        out = ulysses_attention(q, k, v, seq_mesh, causal=causal)
        ref = naive_causal(q, k, v) if causal else dot_product_attention(q, k, v)
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_composes_with_data_axis(self, data_seq_mesh):
        q, k, v = rand_qkv(jax.random.key(1), (4, 32, 4, 8))
        out = ulysses_attention(q, k, v, data_seq_mesh)
        np.testing.assert_allclose(out, dot_product_attention(q, k, v),
                                   atol=2e-5)

    def test_kv_mask_matches_full_attention(self, seq_mesh):
        """Key-padding mask: validity chunks all-gather to the full per-key
        mask before the local attention."""
        q, k, v = rand_qkv(jax.random.key(7), (2, 64, 8, 16))
        valid = jnp.stack([jnp.arange(64) < 40, jnp.ones(64, bool)])
        out = ulysses_attention(q, k, v, seq_mesh, kv_mask=valid)
        ref = dot_product_attention(q, k, v, valid[:, None, None, :])
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_kv_mask_with_flash_inner(self, data_seq_mesh):
        """Padding masks flow through to the Pallas flash inner kernel."""
        from dtf_tpu.ops.flash_attention import flash_attention_impl
        q, k, v = rand_qkv(jax.random.key(8), (2, 32, 4, 8))
        valid = jnp.stack([jnp.arange(32) < 24, jnp.ones(32, bool)])
        out = ulysses_attention(q, k, v, data_seq_mesh,
                                inner=flash_attention_impl(),
                                kv_mask=valid)
        ref = dot_product_attention(q, k, v, valid[:, None, None, :])
        np.testing.assert_allclose(out, ref, atol=2e-5)

    def test_impl_accepts_key_padding_mask(self, seq_mesh):
        q, k, v = rand_qkv(jax.random.key(9), (2, 32, 8, 8))
        valid = jnp.stack([jnp.ones(32, bool), jnp.arange(32) < 16])
        impl = ulysses_attention_impl(seq_mesh)
        out = impl(q, k, v, valid[:, None, None, :])
        ref = dot_product_attention(q, k, v, valid[:, None, None, :])
        np.testing.assert_allclose(out, ref, atol=2e-5)
        with pytest.raises(ValueError, match="per-query"):
            impl(q, k, v, jnp.ones((2, 1, 32, 32), bool))

    def test_under_jit_stays_seq_sharded(self, seq_mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        q, k, v = rand_qkv(jax.random.key(2), (1, 64, 8, 8))
        s = NamedSharding(seq_mesh, P(None, "seq", None, None))
        qs, ks, vs = (jax.device_put(x, s) for x in (q, k, v))

        @jax.jit
        def f(q, k, v):
            return ulysses_attention(q, k, v, seq_mesh, causal=True)

        out = f(qs, ks, vs)
        assert out.sharding.spec == s.spec
        np.testing.assert_allclose(out, naive_causal(q, k, v), atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_flow_through_all_to_alls(self, seq_mesh, causal):
        q, k, v = rand_qkv(jax.random.key(3), (1, 32, 8, 8))

        def f_uly(q, k, v):
            return jnp.sum(ulysses_attention(q, k, v, seq_mesh,
                                             causal=causal) ** 2)

        def f_ref(q, k, v):
            ref = naive_causal(q, k, v) if causal else \
                dot_product_attention(q, k, v)
            return jnp.sum(ref ** 2)

        gu = jax.grad(f_uly, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gu, gn, "qkv"):
            np.testing.assert_allclose(a, b, atol=5e-5, err_msg=f"d{name}")

    def test_flash_inner_kernel(self, data_seq_mesh):
        """The Pallas flash kernel runs as the local attention after the
        all-to-all — sequence parallelism composes with the fused kernel."""
        from dtf_tpu.ops.flash_attention import flash_attention_impl
        q, k, v = rand_qkv(jax.random.key(4), (2, 32, 4, 8))
        out = ulysses_attention(q, k, v, data_seq_mesh,
                                inner=flash_attention_impl(causal=True))
        np.testing.assert_allclose(out, naive_causal(q, k, v), atol=2e-5)

    def test_bf16(self, seq_mesh):
        q, k, v = rand_qkv(jax.random.key(5), (1, 32, 8, 8), jnp.bfloat16)
        out = ulysses_attention(q, k, v, seq_mesh)
        ref = dot_product_attention(q.astype(jnp.float32),
                                    k.astype(jnp.float32),
                                    v.astype(jnp.float32))
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(out.astype(jnp.float32), ref, atol=2e-2)

    def test_indivisible_heads_raises(self, seq_mesh):
        q, k, v = rand_qkv(jax.random.key(6), (1, 32, 4, 8))  # 4 heads, n=8
        with pytest.raises(ValueError, match="head count"):
            ulysses_attention(q, k, v, seq_mesh)

    def test_indivisible_seq_raises(self, seq_mesh):
        q, k, v = rand_qkv(jax.random.key(7), (1, 30, 8, 8))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, seq_mesh)

    def test_missing_axis_raises(self):
        mesh = make_mesh("data=8")
        q, k, v = rand_qkv(jax.random.key(8), (1, 32, 8, 8))
        with pytest.raises(ValueError, match="no 'seq' axis"):
            ulysses_attention(q, k, v, mesh)

    def test_causal_with_inner_raises(self, seq_mesh):
        """`inner` owns masking: passing causal=True alongside it would be
        silently ignored, so it is rejected."""
        from dtf_tpu.ops.flash_attention import flash_attention_impl
        q, k, v = rand_qkv(jax.random.key(9), (1, 32, 8, 8))
        with pytest.raises(ValueError, match="owns masking"):
            ulysses_attention(q, k, v, seq_mesh, causal=True,
                              inner=flash_attention_impl())


class TestUlyssesInModels:
    def test_attn_impl_matches_plain_mha(self, seq_mesh):
        impl = ulysses_attention_impl(seq_mesh)
        mha_uly = MultiHeadAttention(dim=64, num_heads=8, attn_impl=impl)
        mha_ref = MultiHeadAttention(dim=64, num_heads=8)
        params = mha_ref.init(jax.random.key(0))
        x = jax.random.normal(jax.random.key(1), (2, 64, 64))
        np.testing.assert_allclose(mha_uly.apply(params, x),
                                   mha_ref.apply(params, x), atol=2e-5)

    def test_bert_with_ulysses_trains(self, data_seq_mesh):
        """BERT with ulysses attention: one DP+SP train step end to end."""
        from dtf_tpu import optim
        from dtf_tpu.models.bert import BertConfig, BertMLM
        from dtf_tpu.train.trainer import (init_state, make_train_step,
                                           put_global_batch)

        cfg = BertConfig.tiny(
            num_heads=4, attn_impl=ulysses_attention_impl(data_seq_mesh))
        model = BertMLM(cfg)
        opt = optim.adam(1e-3)
        state = init_state(model, opt, seed=0, mesh=data_seq_mesh)
        step = make_train_step(model.loss, opt, data_seq_mesh, donate=False)
        toks = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (8, cfg.max_len)).astype(np.int32)
        batch = put_global_batch(data_seq_mesh, toks)
        state, metrics = step(state, batch, jax.random.key(0))
        assert np.isfinite(float(metrics["loss"]))
        assert int(state["step"]) == 1
