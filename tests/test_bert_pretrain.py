"""BERT pretrain workload CLI: runs across mesh shapes, remat equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dtf_tpu.workloads.bert_pretrain import main


class TestBertPretrainCLI:
    @pytest.mark.parametrize("mesh,extra", [
        ("data=2,fsdp=2,tensor=2", []),
        ("data=4,seq=2", ["--ring_attention"]),
        ("data=4,pipe=2", ["--pipeline_microbatches", "2"]),
    ])
    def test_runs_on_mesh(self, tmp_path, capsys, mesh, extra):
        rc = main(["--preset", "tiny", "--steps", "4", "--batch_size", "16",
                   "--mesh", mesh, "--log_frequency", "2",
                   "--logdir", str(tmp_path)] + extra)
        assert rc == 0
        out = capsys.readouterr().out
        assert "Step-Time:" in out and "Throughput:" in out
        assert "done" in out

    def test_remat_flag_runs(self, tmp_path, capsys):
        rc = main(["--preset", "tiny", "--steps", "3", "--batch_size", "8",
                   "--remat", "--bf16", "--log_frequency", "3",
                   "--logdir", str(tmp_path)])
        assert rc == 0
        assert "Step-Time:" in capsys.readouterr().out

    def test_ring_inside_pipeline_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="pipelined encoder requires"):
            main(["--preset", "tiny", "--steps", "2", "--batch_size", "16",
                  "--mesh", "data=2,seq=2,pipe=2", "--ring_attention",
                  "--pipeline_microbatches", "2", "--logdir", str(tmp_path)])


class TestRemat:
    def test_remat_matches_no_remat(self):
        """jax.checkpoint must not change values or gradients."""
        from dtf_tpu.models.bert import BertConfig, BertMLM

        toks = np.random.default_rng(0).integers(0, 128, (4, 32)).astype(
            np.int32)
        out = {}
        for remat in (False, True):
            cfg = BertConfig.tiny(remat=remat)
            model = BertMLM(cfg)
            params = model.init(jax.random.key(0))

            def loss(params):
                l, _ = model.loss(params, jnp.asarray(toks),
                                  rng=jax.random.key(1))
                return l

            out[remat] = (float(loss(params)),
                          jax.grad(loss)(params))
        assert out[False][0] == pytest.approx(out[True][0], abs=1e-6)
        for a, b in zip(jax.tree_util.tree_leaves(out[False][1]),
                        jax.tree_util.tree_leaves(out[True][1])):
            np.testing.assert_allclose(a, b, atol=1e-5)
