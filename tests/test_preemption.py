"""Preemption-safe training (utils/preemption.py): SIGTERM mid-run ->
checkpoint at the step boundary + clean exit; a --resume run continues
from the preemption step.  Also the topology-change restore path: a
checkpoint written under one mesh restores onto a differently-factored
mesh (the template's shardings win)."""

import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from dtf_tpu.utils.preemption import PreemptionHandler


class TestHandler:
    def test_flag_flips_on_signal(self):
        h = PreemptionHandler(signals=(signal.SIGUSR1,))
        try:
            assert not h.triggered
            os.kill(os.getpid(), signal.SIGUSR1)
            time.sleep(0.05)
            assert h.triggered
        finally:
            h.restore()

    def test_restore_reinstates_previous_handler(self):
        calls = []
        prev = signal.signal(signal.SIGUSR1, lambda *a: calls.append(1))
        try:
            h = PreemptionHandler(signals=(signal.SIGUSR1,))
            h.restore()
            os.kill(os.getpid(), signal.SIGUSR1)
            time.sleep(0.05)
            assert calls == [1]
        finally:
            signal.signal(signal.SIGUSR1, prev)


@pytest.mark.slow
class TestPreemptedRun:
    def test_sigterm_checkpoints_and_resume_continues(self, tmp_path):
        """Drive the real mnist CLI in a subprocess, SIGTERM it mid-epoch,
        then resume: the second run must pick up from the preemption step."""
        # --simulated_devices (config.update), NOT env vars: this image's
        # sitecustomize imports jax first, and the axon TPU plugin would win
        # over JAX_PLATFORMS=cpu in a fresh subprocess.
        env = dict(os.environ)
        args = [sys.executable, "-m", "dtf_tpu.workloads.mnist",
                "--simulated_devices", "8",
                "--epochs", "50", "--batch_size", "200",
                "--logdir", str(tmp_path),
                "--checkpoint_every", "1000000",   # only preemption saves
                "--log_frequency", "5"]
        p = subprocess.Popen(args, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
        # wait until training demonstrably progresses, then preempt
        deadline = time.time() + 300
        lines = []
        for line in p.stdout:
            lines.append(line)
            if line.startswith("Step: ") or time.time() > deadline:
                break
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=300)
        lines.append(out)
        text = "".join(lines)
        assert p.returncode == 0, f"preempted run failed:\n{text[-3000:]}"
        assert "preempted: checkpointed step" in text, text[-3000:]

        ckpts = os.listdir(str(tmp_path / "checkpoints"))
        steps = [int(d) for d in ckpts if d.isdigit()]
        assert steps, f"no checkpoint written: {ckpts}"

        # synthetic MNIST: 12800 train examples / batch 200 = 64 steps/epoch
        resume = subprocess.run(
            args + ["--resume", "--epochs", str(max(steps) // 64 + 1)],
            env=env, capture_output=True, text=True, timeout=300)
        assert resume.returncode == 0, resume.stdout[-3000:]
        assert f"resumed from step {max(steps)}" in resume.stdout


class TestTopologyChangeRestore:
    def test_restore_onto_different_mesh_factoring(self, tmp_path):
        """Save under data=8, restore under data=4 x tensor=2: values equal,
        shardings follow the new template (elastic topology resume)."""
        from dtf_tpu import optim
        from dtf_tpu.models.mlp import MnistMLP
        from dtf_tpu.parallel import sharding as sh
        from dtf_tpu.parallel.mesh import make_mesh
        from dtf_tpu.train.checkpoint import CheckpointManager
        from dtf_tpu.train.trainer import init_state, make_train_step, put_global_batch

        model = MnistMLP(init_scale="fan_in")
        opt = optim.sgd(0.1)

        mesh_a = make_mesh("data=8")
        state = init_state(model, opt, seed=1, mesh=mesh_a)
        step = make_train_step(model.loss, opt, mesh_a, donate=False)
        batch = put_global_batch(
            mesh_a, (np.random.default_rng(0).random((16, 784), np.float32),
                     np.eye(10, dtype=np.float32)[np.arange(16) % 10]))
        state, _ = step(state, batch, jax.random.key(0))
        ckpt = CheckpointManager(str(tmp_path / "ck"), async_save=False)
        ckpt.save(1, state, force=True)
        ckpt.wait()

        mesh_b = make_mesh("data=4,tensor=2")
        rules = sh.apply_rules(model.axes(), mesh_b)
        template = init_state(model, opt, seed=99, mesh=mesh_b,
                              param_shardings=rules)
        restored, at = CheckpointManager(str(tmp_path / "ck")).restore(template)
        assert at == 1
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)),
            restored["params"], state["params"])
        w1 = restored["params"]["l1"]["w"]
        assert w1.sharding.mesh.shape == mesh_b.shape
