"""Fleet-plane worker (spawned by tests/test_fleet.py and the full-suite
fleet lane).

One "host" of an N-host job with the FLEET plane armed for real: the
plane is configured with this host's explicit identity BEFORE the
Trainer is built (the same out-of-band pattern as tests/_mp_health.py —
these hosts are independent single-process jax instances, so
``jax.process_index()`` cannot name them), every host's span stream
lands in the SHARED logdir under its fleet index, barrier arrivals
travel the ``--fleet_dir`` file mesh, and host 0 serves ``/fleetz`` when
an admin port is passed and writes the ``fleet.json`` rollup the
post-hoc ``report --fleet`` judges.

Hosts rendezvous through the mesh before training so compile-time skew
between children doesn't pollute the first barriers' blame — the
attribution tests pin WHERE the blame lands, and it must land on the
chaos-injected straggler, not on whichever child compiled slower.

Usage:
    _mp_fleet.py <task> <nproc> <shared_dir> <max_steps> <devices>
                 [chaos] [admin_port]

Host 0 prints ``MP_FLEET_DONE steps=<n> final_cost=<loss>``.
"""

import os
import sys


def tiny_splits(n=2048, seed=0):
    """Deterministic, learnable 10-class data — identical on every host
    (the _mp_health.py recipe)."""
    import numpy as np

    from dtf_tpu.data.datasets import Dataset, DataSplits

    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n)
    protos = rng.normal(0, 1, (10, 784)).astype(np.float32)
    x = (protos[y] + rng.normal(0, 2.0, (n, 784))).astype(np.float32)
    return DataSplits(train=Dataset(x, np.eye(10, dtype=np.float32)[y],
                                    seed=1), test=None)


def main(task: int, nproc: int, shared: str, max_steps: int,
         devices: int, chaos: str = "", admin_port: str = "") -> int:
    from dtf_tpu import optim
    from dtf_tpu.cluster import bootstrap
    from dtf_tpu.config import ClusterConfig, TrainConfig
    from dtf_tpu.models.mlp import MnistMLP
    from dtf_tpu.resilience.chaos import FaultPlan
    from dtf_tpu.telemetry import fleet
    from dtf_tpu.train.trainer import Trainer

    cluster = bootstrap(ClusterConfig(simulated_devices=devices,
                                      mesh="data=-1"))
    # Host 0 owns the SHARED logdir (telemetry.json / metrics.csv /
    # checkpoints / fleet.json); other hosts keep their own books in a
    # scratch logdir — but every host's SPAN stream goes to the shared
    # logdir under its fleet index (the plane's spans_dir), which is what
    # makes the cross-host trace merge possible.
    logdir = (os.path.join(shared, "logs") if task == 0
              else os.path.join(shared, f"logs_task{task}"))
    plane = fleet.configure(os.path.join(shared, "fleet"), task, nproc,
                            spans_dir=os.path.join(shared, "logs"))
    cfg = TrainConfig(
        batch_size=64, learning_rate=0.05, epochs=100,
        log_frequency=2, seed=1, logdir=logdir,
        checkpoint_every=5, prefetch=0,
        admin_port=(int(admin_port) if admin_port and task == 0
                    else None))
    plan = FaultPlan.parse(chaos, process_index=task) if chaos else None
    trainer = Trainer(cluster, MnistMLP(init_scale="fan_in"),
                      optim.sgd(0.05), cfg, chaos=plan)
    # Warm the step compile BEFORE the rendezvous (step_fn donates its
    # first argument, so warm a throwaway copy), then align every host's
    # loop entry through the mesh: compile-time skew must not be the
    # thing the skew attribution measures.
    import jax
    import numpy as np

    from dtf_tpu.train.trainer import put_global_batch

    dummy = put_global_batch(
        cluster.mesh, (np.zeros((cfg.batch_size, 784), np.float32),
                       np.zeros((cfg.batch_size, 10), np.float32)))
    throwaway = jax.tree_util.tree_map(lambda x: x + 0, trainer.state)
    jax.block_until_ready(
        trainer.step_fn(throwaway, dummy, jax.random.key(0)))
    plane.rendezvous(120.0)
    try:
        result = trainer.fit(tiny_splits(), max_steps=max_steps)
    finally:
        if trainer.ckpt is not None:
            trainer.ckpt.close()
    if task == 0:
        print(f"MP_FLEET_DONE steps={result['steps']} "
              f"final_cost={result['final_cost']:.6f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                  int(sys.argv[4]), int(sys.argv[5]),
                  sys.argv[6] if len(sys.argv) > 6 else "",
                  sys.argv[7] if len(sys.argv) > 7 else ""))
