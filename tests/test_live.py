"""Live introspection plane (ISSUE 11): per-request tracing
(telemetry/reqtrace.py), admin endpoint (telemetry/live.py), SLO
burn-rate monitor (telemetry/slo.py), registry snapshot consistency,
and span-file rotation.

The ISSUE-level pins live here:

* **/statz consistency** — a snapshot taken while writer threads update
  counter PAIRS under ``registry.locked()`` never observes a torn pair;
* **/tracez ring eviction order** — oldest terminal trace evicted
  first, a replayed trace re-terminates at the back;
* **trace completeness** — every completed request of a chaos'd
  closed-loop run reconstructs a gap-free admission->prefill->
  first_token->completion chain from the span files, INCLUDING across a
  drain + replay (trace-id continuity);
* **alert-leads-control** — under the pinned slow_decode spike the SLO
  monitor's fast-burn alert fires strictly before the brownout
  controller escalates to reject_all.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import dtf_tpu.telemetry as tel
from dtf_tpu.telemetry import reqtrace
from dtf_tpu.telemetry.live import AdminServer, LivenessProbe
from dtf_tpu.telemetry.registry import MetricRegistry
from dtf_tpu.telemetry.reqtrace import TraceRing
from dtf_tpu.telemetry.slo import BurnRateMonitor, SLOSpec
from dtf_tpu.telemetry.spans import Tracer, find_span_files, read_spans


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    tel.reset()
    yield
    tel.reset()


# ---------------------------------------------------------------------------
# Registry: consistent snapshots, strict registration
# ---------------------------------------------------------------------------


class TestRegistryConsistency:
    def test_snapshot_never_tears_a_locked_pair(self):
        """Writers increment two counters as one locked group; every
        concurrent snapshot must see them EQUAL — the /statz contract."""
        reg = MetricRegistry()
        a = reg.counter("serve/shed_total")
        b = reg.counter("serve/shed_deadline_expired")
        stop = threading.Event()
        torn = []

        def writer():
            while not stop.is_set():
                with reg.locked():
                    a.inc()
                    b.inc()

        def reader():
            while not stop.is_set():
                snap = reg.snapshot()
                va = snap["serve/shed_total"]["value"]
                vb = snap["serve/shed_deadline_expired"]["value"]
                if va != vb:
                    torn.append((va, vb))

        threads = ([threading.Thread(target=writer) for _ in range(2)]
                   + [threading.Thread(target=reader) for _ in range(2)])
        for t in threads:
            t.start()
        import time
        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join()
        assert a.value == b.value > 0
        assert not torn, f"torn snapshots observed: {torn[:5]}"

    def test_strict_registry_rejects_undeclared(self):
        with pytest.raises(ValueError, match="not declared"):
            tel.counter("bogus/never_declared")
        # exact and pattern-covered names still register
        tel.counter("checkpoint/saves_total").inc()
        tel.counter("serve/shed_some_new_reason").inc()
        tel.gauge("serve/slo_burn_ttft_fast").set(1.5)

    def test_scratch_registry_stays_shape_only(self):
        reg = MetricRegistry()
        reg.counter("anything/goes_here").inc()       # undeclared: fine
        with pytest.raises(ValueError):
            reg.counter("Not Snake Case")             # shape still holds

    def test_locked_is_reentrant(self):
        reg = MetricRegistry()
        with reg.locked():
            with reg.locked():
                reg.counter("a/b").inc()
        assert reg.snapshot()["a/b"]["value"] == 1


# ---------------------------------------------------------------------------
# Span-file rotation
# ---------------------------------------------------------------------------


class TestSpanRotation:
    def test_rotate_and_keep_last(self, tmp_path):
        path = str(tmp_path / "spans.p0.jsonl")
        tr = Tracer(path, process=0, max_bytes=1500, keep=2)
        for i in range(300):
            tr.instant("event/tick", i=i)
        tr.close()
        files = find_span_files(str(tmp_path))
        names = [f.split("/")[-1] for f in files]
        # active file last, rotated generations before it, only keep=2
        assert names[-1] == "spans.p0.jsonl"
        rotated = names[:-1]
        assert 1 <= len(rotated) <= 2
        assert all(n.startswith("spans.p0.") and n.endswith(".jsonl")
                   for n in rotated)
        # the newest records survive in the retained set
        recs = [r for f in files for r in read_spans(f)]
        assert recs[-1]["args"]["i"] == 299
        # rotated files are in generation order (reader sees one stream)
        seqs = [int(n.split(".")[2]) for n in rotated]
        assert seqs == sorted(seqs)

    def test_rotation_resumes_numbering(self, tmp_path):
        path = str(tmp_path / "spans.p0.jsonl")
        for _round in range(2):
            tr = Tracer(path, process=0, max_bytes=800, keep=10)
            for i in range(100):
                tr.instant("event/tick", i=i)
            tr.close()
        seqs = sorted(int(f.split(".")[-2])
                      for f in find_span_files(str(tmp_path))
                      if f.split("/")[-1].count(".") == 3)
        assert seqs == sorted(set(seqs)), "rotation seq collided"

    def test_unrotated_default_unchanged(self, tmp_path):
        tr = Tracer(str(tmp_path / "spans.p0.jsonl"))
        for i in range(100):
            tr.instant("event/tick", i=i)
        tr.close()
        assert len(find_span_files(str(tmp_path))) == 1


# ---------------------------------------------------------------------------
# Flight recorder ring
# ---------------------------------------------------------------------------


class TestTraceRing:
    def _finish(self, ring, tid, rid, status="completed"):
        ring.event(tid, rid, "submit", 0.0)
        ring.event(tid, rid, status, 1.0)

    def test_eviction_order_is_terminal_order(self):
        ring = TraceRing(capacity=3)
        for rid in range(5):
            self._finish(ring, f"t{rid}", rid)
        snap = ring.snapshot()
        assert [d["rid"] for d in snap] == [2, 3, 4]   # oldest evicted
        assert len(ring) == 3

    def test_replay_reterminates_at_the_back(self):
        ring = TraceRing(capacity=2)
        self._finish(ring, "ta", 0, status="drained")
        self._finish(ring, "tb", 1)
        # replay of ta: same trace id, second terminal -> back of ring
        ring.event("ta", 0, "submit", 2.0, resubmit=True)
        ring.event("ta", 0, "completed", 3.0)
        snap = ring.snapshot()
        assert [d["trace_id"] for d in snap] == ["tb", "ta"]
        # the replayed doc kept BOTH segments' events
        assert [e["phase"] for e in snap[1]["events"]] == [
            "submit", "drained", "submit", "completed"]

    def test_snapshot_n_keeps_newest(self):
        ring = TraceRing(capacity=8)
        for rid in range(5):
            self._finish(ring, f"t{rid}", rid)
        assert [d["rid"] for d in ring.snapshot(2)] == [3, 4]
        assert ring.snapshot(0) == []     # count probe, not a full dump

    def test_live_traces_not_exposed(self):
        ring = TraceRing(capacity=2)
        ring.event("tx", 7, "submit", 0.0)
        assert ring.snapshot() == []                  # not terminal yet


# ---------------------------------------------------------------------------
# Burn-rate math
# ---------------------------------------------------------------------------


class TestBurnRate:
    def test_burn_is_bad_fraction_over_budget(self):
        mon = BurnRateMonitor([SLOSpec("ttft", 0.99, fast_window_s=10,
                                       slow_window_s=100, min_events=4)])
        for i in range(8):
            mon.record("ttft", bad=(i < 2), t=1.0 + i * 0.1)
        out = mon.update(2.0, iteration=0)
        # 2 bad / 8 events = 0.25 bad frac; budget 0.01 -> burn 25
        assert out["ttft"]["fast_burn"] == pytest.approx(25.0)
        assert out["ttft"]["slow_burn"] == pytest.approx(25.0)

    def test_min_events_guards_noise(self):
        mon = BurnRateMonitor([SLOSpec("ttft", 0.99, fast_window_s=10,
                                       slow_window_s=100, min_events=4)])
        for i in range(3):
            mon.record("ttft", bad=True, t=float(i))
        out = mon.update(3.0, iteration=0)
        assert out["ttft"]["fast_burn"] == 0.0        # 3 < min_events
        assert not out["ttft"]["fast_firing"]

    def test_window_trims_old_events(self):
        mon = BurnRateMonitor([SLOSpec("ttft", 0.9, fast_window_s=5,
                                       slow_window_s=50, min_events=1)])
        for i in range(10):
            mon.record("ttft", bad=True, t=float(i))   # t in [0, 9]
        # at t=100 every event is outside even the slow window
        out = mon.update(100.0, iteration=0)
        assert out["ttft"]["fast_window_events"] == 0
        assert out["ttft"]["fast_burn"] == 0.0

    def test_alert_edge_triggered_and_first_alert_pinned(self):
        mon = BurnRateMonitor([SLOSpec("ttft", 0.99, fast_window_s=10,
                                       slow_window_s=100, min_events=2,
                                       fast_burn=14.4)])
        for i in range(4):
            mon.record("ttft", bad=True, t=1.0 + 0.1 * i)
        mon.update(2.0, iteration=5)                  # fires (edge)
        mon.update(2.1, iteration=6)                  # still firing: no re-count
        st = mon.state()["objectives"]["ttft"]
        assert st["alerts_fast"] == 1
        assert st["firing_fast"]
        assert mon.first_alert("ttft") == (2.0, 5)
        assert tel.counter("serve/slo_alert_fast_total").value == 1
        # recovery then relapse: a second excursion counts again
        for i in range(50):
            mon.record("ttft", bad=False, t=3.0 + 0.01 * i)
        mon.update(4.0, iteration=20)
        assert not mon.state()["objectives"]["ttft"]["firing_fast"]
        for i in range(60):
            mon.record("ttft", bad=True, t=4.1 + 0.01 * i)
        mon.update(5.0, iteration=30)
        assert mon.state()["objectives"]["ttft"]["alerts_fast"] == 2
        assert mon.first_alert("ttft") == (2.0, 5)    # FIRST stays first

    def test_for_serving_shapes(self):
        mon = BurnRateMonitor.for_serving(400.0, slo_tpot_ms=50.0)
        assert mon.has("ttft") and mon.has("tpot") and mon.has("deadline")
        assert mon.slo_ttft_ms == 400.0
        st = mon.state()
        assert st["slo_ttft_ms"] == 400.0
        assert st["objectives"]["deadline"]["target"] == 0.999

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="target"):
            SLOSpec("x", 1.5)
        with pytest.raises(ValueError, match="shorter"):
            SLOSpec("x", 0.99, fast_window_s=100, slow_window_s=10)
        with pytest.raises(ValueError, match="objective"):
            BurnRateMonitor([])


# ---------------------------------------------------------------------------
# Admin endpoint
# ---------------------------------------------------------------------------


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as r:
        return r.status, json.loads(r.read())


class TestAdminServer:
    def test_endpoints_and_payloads(self):
        ring = TraceRing(4)
        ring.event("tt", 1, "submit", 0.0)
        ring.event("tt", 1, "completed", 0.5)
        mon = BurnRateMonitor.for_serving(400.0)
        probe = LivenessProbe(stale_after_s=60.0)
        srv = AdminServer(0, probe=probe, trace_ring=ring, slo=mon).start()
        try:
            probe.beat(12)
            tel.counter("serve/requests_completed").inc(3)
            code, statz = _get(srv.port, "/statz")
            assert code == 200
            assert statz["metrics"]["serve/requests_completed"][
                "value"] == 3
            assert "goodput" in statz
            code, health = _get(srv.port, "/healthz")
            assert code == 200 and health["ok"] and health["beats"] == 12
            code, tracez = _get(srv.port, "/tracez")
            assert code == 200 and tracez["count"] == 1
            assert tracez["traces"][0]["trace_id"] == "tt"
            code, slo = _get(srv.port, "/slo")
            assert code == 200 and "objectives" in slo
            code, idx = _get(srv.port, "/")
            assert code == 200 and "/statz" in idx["endpoints"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.port, "/nope")
            assert ei.value.code == 404
        finally:
            srv.close()

    def test_healthz_flips_on_stale_beat(self):
        probe = LivenessProbe(stale_after_s=0.05)
        srv = AdminServer(0, probe=probe).start()
        try:
            # never beaten: booting is OK (the loop may still be in init)
            code, doc = _get(srv.port, "/healthz")
            assert code == 200 and doc["phase"] == "booting"
            probe.beat(1)
            import time
            time.sleep(0.2)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.port, "/healthz")
            assert ei.value.code == 503
        finally:
            srv.close()

    def test_statz_scrape_is_consistent_under_writers(self):
        """The /statz half of the torn-pair pin: HTTP scrapes race real
        writer threads updating a locked pair."""
        srv = AdminServer(0).start()
        stop = threading.Event()

        def writer():
            reg = tel.get_registry()
            a = reg.counter("serve/shed_total")
            b = reg.counter("serve/shed_deadline_expired")
            while not stop.is_set():
                with reg.locked():
                    a.inc()
                    b.inc()

        w = threading.Thread(target=writer)
        w.start()
        try:
            for _ in range(20):
                _, doc = _get(srv.port, "/statz")
                m = doc["metrics"]
                if "serve/shed_total" not in m:
                    continue
                assert (m["serve/shed_total"]["value"]
                        == m["serve/shed_deadline_expired"]["value"])
        finally:
            stop.set()
            w.join()
            srv.close()


# ---------------------------------------------------------------------------
# Engine integration: trace completeness, drain/replay continuity,
# alert-leads-control (jax; shares the serve marker)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_model():
    import jax
    from dtf_tpu.models.gpt import GPT, GPTConfig
    model = GPT(GPTConfig.tiny())
    return model, model.init(jax.random.key(0))


def _mk_trace(n, *, qps=40.0, seed=3, deadline_ms=None, vocab=128):
    rng = np.random.default_rng(seed)
    trace, t = [], 0.0
    for rid in range(n):
        t += float(rng.exponential(1.0)) / qps
        kw = {"rid": rid,
              "prompt": rng.integers(0, vocab, (int(rng.choice([3, 5, 8])),)
                                     ).astype(np.int32),
              "max_new_tokens": int(rng.choice([2, 4, 6]))}
        if deadline_ms is not None:
            kw["deadline_ms"] = deadline_ms
        trace.append((t, kw))
    return trace


@pytest.mark.serve
class TestReqTraceEngine:
    def _engine(self, tiny_model, **kw):
        from dtf_tpu.serve import ServingEngine, VirtualClock
        model, params = tiny_model
        kw.setdefault("clock", VirtualClock())
        kw.setdefault("num_slots", 3)
        kw.setdefault("block_size", 4)
        kw.setdefault("blocks_per_slot", 8)
        return ServingEngine(model, params, **kw)

    def test_chaosd_run_traces_are_complete(self, tiny_model, tmp_path):
        """Every completed request of a chaos'd closed-loop run leaves a
        gap-free admission->completion chain in the span files; the
        evicted/cancelled victims leave attributed terminal events."""
        from dtf_tpu.resilience.chaos import FaultPlan
        logdir = str(tmp_path)
        tel.configure(logdir)
        chaos = FaultPlan.parse(
            "slow_decode@6:30ms,client_drop@4,kv_poison@8",
            process_index=0)
        eng = self._engine(tiny_model, chaos=chaos)
        eng.run(_mk_trace(16))
        tel.get_tracer().flush()
        traces = reqtrace.group_traces(
            reqtrace.load_request_events(logdir))
        comp = reqtrace.completeness(traces)
        done = sum(1 for r in eng.results.values()
                   if r.status == "completed")
        assert comp["completed"] == done > 0
        assert comp["complete_frac"] == 1.0, comp["incomplete"]
        # chaos victims are attributed, not vanished
        statuses = {t[-1]["phase"] for t in
                    ([evs for evs in traces.values()])}
        by_status = {}
        for evs in traces.values():
            term = [e for e in evs if e["phase"] in reqtrace.TERMINAL]
            assert term, "trace with no terminal event"
            by_status[term[-1]["phase"]] = by_status.get(
                term[-1]["phase"], 0) + 1
        assert by_status.get("cancelled", 0) >= 1     # client_drop victim
        assert by_status.get("failed", 0) >= 1        # kv_poison victim
        # and the flight recorder holds the same terminal set
        assert len(eng.reqtrace.ring) == len(traces)

    def test_trace_continuity_across_drain_and_replay(self, tiny_model,
                                                      tmp_path):
        """drain.jsonl replay docs carry the original trace id: the
        replay engine's timeline joins the pre-drain one into ONE
        complete per-request story (ISSUE 11 satellite)."""
        logdir = str(tmp_path)
        tel.configure(logdir)
        eng = self._engine(tiny_model)
        real_step = eng.step

        def draining_step():
            if eng.iterations == 3:
                eng.request_drain()
            return real_step()

        eng.step = draining_step
        eng.run(_mk_trace(10), drain_timeout_s=0.0)
        assert eng.drained and eng.drain_docs, "drain produced no docs"
        for doc in eng.drain_docs:
            assert doc["trace_id"], "replay doc lost the trace id"
        drained_ids = {d["rid"]: d["trace_id"] for d in eng.drain_docs}

        # fresh engine = the supervisor's replay attempt
        eng2 = self._engine(tiny_model)
        for doc in eng.drain_docs:
            assert doc["resubmit"] is True    # replay provenance is explicit
            eng2.submit(np.asarray(doc["prompt"], np.int32),
                        doc["max_new_tokens"],
                        temperature=doc["temperature"],
                        eos_id=doc["eos_id"],
                        deadline_ms=doc["deadline_ms"],
                        priority=doc["priority"], rid=doc["rid"],
                        trace_id=doc["trace_id"],
                        resubmit=doc["resubmit"])
        eng2.run([])
        tel.get_tracer().flush()
        traces = reqtrace.group_traces(
            reqtrace.load_request_events(logdir))
        for rid, tid in drained_ids.items():
            evs = traces[tid]
            phases = [e["phase"] for e in evs]
            # two segments under ONE id: drained then replayed-to-done
            assert phases.count("submit") == 2
            assert "drained" in phases
            assert phases[-1] == "completed" or "completed" in phases
            assert not reqtrace.chain_gaps(evs), (rid, phases)
            # the replay segment is marked
            resub = [e for e in evs if e.get("resubmit")]
            assert len(resub) == 1
        comp = reqtrace.completeness(traces)
        assert comp["complete_frac"] == 1.0

    def test_alert_leads_control_under_pinned_spike(self, tiny_model):
        """The tentpole's same-trace CI claim, pinned as a unit test:
        fast-burn fires strictly before brownout reject_all."""
        from dtf_tpu.resilience.chaos import FaultPlan
        from dtf_tpu.serve import BrownoutController
        mon = BurnRateMonitor.for_serving(120.0)
        eng = self._engine(
            tiny_model,
            brownout=BrownoutController(120.0),
            chaos=FaultPlan.parse("slow_decode@8:40ms", process_index=0),
            slo=mon, max_queue=256)
        eng.run(_mk_trace(40, qps=30.0, deadline_ms=4000.0))
        ra = eng.brownout.first_transition_to(3)
        alert = mon.first_alert("ttft")
        assert ra is not None, "pinned spike never reached reject_all"
        assert alert is not None, "fast-burn alert never fired"
        assert alert[1] < ra, (alert, ra)
        # and summary() carries both marks for the bench gate
        s = eng.summary(slo_ttft_ms=120.0)
        assert s["brownout"]["reject_all_iteration"] == ra
        assert (s["slo"]["objectives"]["ttft"]["first_alert"]["fast"]
                ["iteration"] == alert[1])

    def test_report_request_view_and_trace_gate(self, tiny_model,
                                                tmp_path, capsys):
        from dtf_tpu.telemetry import report as rep
        logdir = str(tmp_path)
        tel.configure(logdir)
        eng = self._engine(tiny_model)
        eng.run(_mk_trace(6))
        eng.write_telemetry(logdir, slo_ttft_ms=400.0)
        tel.get_tracer().flush()
        report = rep.build_report(logdir)
        rt = report["request_traces"]
        assert rt["complete_frac"] == 1.0
        ok, lines = rep.check_gates(report, min_trace_complete_frac=0.99)
        assert ok, lines
        # a stricter-than-perfect bound fails (falsifiability)
        ok, lines = rep.check_gates(report, min_trace_complete_frac=1.01)
        assert not ok
        # the --request CLI view renders a timeline for a real rid
        rid = next(r.rid for r in eng.results.values()
                   if r.status == "completed")
        rc = rep.main([logdir, "--request", str(rid)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "first_token" in out and "completed" in out
        assert "engine_decode" in out     # iteration spans interleaved
        # rendered report shows the section
        text = rep.render(report)
        assert "Request traces" in text and "complete_frac" in text

    def test_trace_gate_fails_without_events(self, tiny_model, tmp_path):
        """Absence is not a pass: a logdir with no reqtrace events fails
        the armed gate (same rule as every other gate)."""
        from dtf_tpu.telemetry import report as rep
        report = rep.build_report(str(tmp_path))
        ok, lines = rep.check_gates(report, min_trace_complete_frac=0.99)
        assert not ok
        assert any("not measured" in ln for ln in lines)
